"""Importable serve application for YAML-deploy tests (the
import_path target — reference configs point at modules the same way)."""

from ray_tpu import serve


@serve.deployment(name="Adder")
class Adder:
    def __init__(self, bias: int = 0):
        self.bias = bias

    def __call__(self, payload):
        return {"sum": payload.get("x", 0) + self.bias}


@serve.deployment(name="Front")
class Front:
    def __init__(self, adder):
        self._adder = adder

    def __call__(self, payload):
        out = self._adder.remote(payload).result(timeout=30)
        return {"front": True, **out}


#: bound graph referenced as tests.serve_app_fixture:app
app = Front.bind(Adder.bind(5))


def build(bias: int = 5):
    """Builder form: import_path tests.serve_app_fixture:build + args."""
    return Front.bind(Adder.bind(bias))
