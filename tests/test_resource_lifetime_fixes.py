"""Regression tests for the real leaks the ``res`` lint family
surfaced in-tree (PR 12): the serve controller's per-deployment version
dicts, the driver's per-actor conn registry, and the client runtime's
unjoined ref-flusher. Each test pins the FIX's behavior — delete/kill
paths must shrink the registry they previously grew forever.
"""

from __future__ import annotations

import threading
import time

from ray_tpu.devtools.lock_debug import make_lock, make_rlock


# ------------------------------------------------ controller version dicts


def make_controller():
    """A bare ServeController (no reconcile loop, no cluster) with just
    the replica-set/version machinery wired — the unit idiom
    test_serve_routing.py uses for the Router."""
    from ray_tpu.serve._private.controller import ServeController

    c = ServeController.__new__(ServeController)
    c._lock = make_rlock("serve.controller._lock")
    c._set_cond = threading.Condition(c._lock)
    c._deployments = {}
    c._set_versions = {}
    c._load_gens = {}
    c._version_clock = 0
    c._stop_replicas = lambda replicas: None
    return c


def test_delete_pops_version_entries():
    """The leak: _set_versions/_load_gens grew one entry per deployment
    NAME ever created, forever. delete() must pop both."""
    c = make_controller()
    for i in range(5):
        name = f"dep-{i}"
        c._deployments[name] = {"replicas": []}
        with c._lock:
            c._bump_set(name)
        c._load_gens[name] = c._version_clock
        assert c.delete(name)
    assert c._set_versions == {}
    assert c._load_gens == {}
    assert c._deployments == {}


def test_version_clock_never_remints_a_seen_version():
    """Popping on delete is only safe because versions are minted from
    one monotonic clock: a redeploy must never reuse a version a parked
    router already saw (the != comparator would park through the change
    forever)."""
    c = make_controller()
    seen = set()
    for _ in range(3):
        c._deployments["d"] = {"replicas": []}
        with c._lock:
            c._bump_set("d")
        v = c._set_versions["d"]
        assert v not in seen
        seen.add(v)
        assert c.delete("d")
    # Deleted state reads version 0 — also never minted.
    assert 0 not in seen


def test_parked_poller_wakes_on_delete_then_reparks():
    c = make_controller()
    c._deployments["d"] = {"replicas": ["r1"]}
    with c._lock:
        c._bump_set("d")
    known = c._set_versions["d"]
    got = []

    def poll():
        got.append(c.listen_for_change("d", known, timeout=10.0))

    t = threading.Thread(target=poll, daemon=True)
    t.start()
    time.sleep(0.2)
    assert c.delete("d")
    t.join(timeout=5.0)
    assert not t.is_alive()
    v, replicas = got[0]
    assert replicas is None  # poller observed the deletion...
    assert v != known        # ...at a version it had not seen
    # And a fresh poll at the post-delete version PARKS (no 1-RPC/s
    # spin against a deleted deployment).
    t0 = time.monotonic()
    v2, replicas2 = c.listen_for_change("d", v, timeout=0.4)
    assert time.monotonic() - t0 >= 0.35
    assert v2 == v and replicas2 is None


# ------------------------------------------------- driver actor registry


def make_core():
    from ray_tpu.core.cluster_core import ClusterCore
    import collections

    core = ClusterCore.__new__(ClusterCore)
    core._actors = {}
    core._actors_lock = make_lock("cluster_core._actors_lock")
    core._dead_actor_reasons = collections.OrderedDict()
    return core


def test_retired_actor_conn_leaves_registry():
    from ray_tpu.core.ids import ActorID

    core = make_core()
    aid = ActorID(b"a" * 12)
    conn = core._actor_conn(aid)
    assert aid in core._actors
    conn.dead = True
    conn.death_reason = "killed via ray_tpu.kill"
    core._retire_actor_conn(conn)
    assert aid not in core._actors  # the per-actor leak is reclaimed
    # A late call still fails fast with the real cause, via an
    # EPHEMERAL conn that is NOT re-registered.
    late = core._actor_conn(aid)
    assert late.dead and late.death_reason == "killed via ray_tpu.kill"
    assert aid not in core._actors


def test_dead_actor_memo_bounded():
    from ray_tpu.core.ids import ActorID

    core = make_core()
    for i in range(4100):
        aid = ActorID(i.to_bytes(12, "big"))
        conn = core._actor_conn(aid)
        conn.dead = True
        conn.death_reason = f"r{i}"
        core._retire_actor_conn(conn)
    assert core._actors == {}
    assert len(core._dead_actor_reasons) == 4096
    # Oldest retirements fell off; newest kept.
    assert ActorID((0).to_bytes(12, "big")) not in \
        core._dead_actor_reasons
    assert ActorID((4099).to_bytes(12, "big")) in \
        core._dead_actor_reasons


# ------------------------------------------------- client ref-flusher join


def test_client_shutdown_joins_flusher_promptly(monkeypatch):
    """The flusher slept a full client_ref_flush_period_s per lap with
    no wake event: shutdown() left it running (daemon) against a closed
    connection. The stop event must wake it and shutdown must join it —
    well inside one flush period."""
    from ray_tpu.client.runtime import ClientRuntime
    from ray_tpu.core.config import GLOBAL_CONFIG as cfg

    old = cfg.get("client_ref_flush_period_s")
    cfg.set("client_ref_flush_period_s", 60.0)
    try:
        rt = ClientRuntime.__new__(ClientRuntime)
        rt._shutdown = False
        rt._stop_event = threading.Event()
        rt._holds_buf = []
        rt._holds_lock = threading.Lock()
        rt._flush_lock = threading.Lock()

        class _Refcount:
            def take_dropped(self):
                return []

            def count(self, o):
                return 1

        class _Conn:
            closed = False

            def call(self, *a, **kw):
                return None

            def notify(self, *a, **kw):
                return None

            def close(self):
                self.closed = True

        rt.refcount = _Refcount()
        rt._conn = _Conn()
        rt._flusher = threading.Thread(target=rt._flush_loop,
                                       daemon=True)
        rt._flusher.start()
        time.sleep(0.1)
        t0 = time.monotonic()
        rt.shutdown()
        assert time.monotonic() - t0 < 10.0  # not one 60s sleep lap
        assert not rt._flusher.is_alive()
        assert rt._conn.closed
    finally:
        cfg.set("client_ref_flush_period_s", old)
