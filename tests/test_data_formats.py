"""Interchange-format connectors: tfrecord/Example codec, webdataset tar
shards, avro container decoding, and the from_torch/from_huggingface
interop constructors (reference analog: data/tests for tfrecords/webdataset/
avro datasources)."""

import io
import json
import struct
import zlib

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rdata
from ray_tpu.data import formats


@pytest.fixture(scope="module")
def cluster():
    rt = ray_tpu.init(num_cpus=2, ignore_reinit_error=True)
    yield rt
    ray_tpu.shutdown()


def test_crc32c_known_vectors():
    # RFC 3720 test vectors.
    assert formats.crc32c(b"") == 0x0
    assert formats.crc32c(b"123456789") == 0xE3069283
    assert formats.crc32c(bytes(32)) == 0x8A9136AA


def test_example_proto_roundtrip():
    feats = {
        "label": 3,
        "weights": [1.5, -2.25],
        "name": b"sample-1",
        "tags": [b"a", b"b", b"c"],
    }
    parsed = formats.parse_example(formats.encode_example(feats))
    assert parsed["label"] == [3]
    np.testing.assert_allclose(parsed["weights"], [1.5, -2.25])
    assert parsed["name"] == [b"sample-1"]
    assert parsed["tags"] == [b"a", b"b", b"c"]


def test_tfrecords_roundtrip_through_dataset(cluster, tmp_path):
    ds = rdata.from_numpy({
        "x": np.arange(10, dtype=np.int64),
        "y": np.linspace(0, 1, 10).astype(np.float32),
    }, parallelism=2)
    out = ds.write_tfrecords(str(tmp_path / "tfr"))
    assert out and all(p.endswith(".tfrecords") for p in out)

    back = rdata.read_tfrecords(str(tmp_path / "tfr")).materialize()
    rows = sorted(back.take_all(), key=lambda r: r["x"])
    assert [r["x"] for r in rows] == list(range(10))
    np.testing.assert_allclose([r["y"] for r in rows],
                               np.linspace(0, 1, 10), rtol=1e-6)


def test_tfrecords_crc_detects_corruption(tmp_path):
    path = str(tmp_path / "x.tfrecords")
    formats.write_tfrecord_file(path, [b"hello world"])
    raw = bytearray(open(path, "rb").read())
    raw[14] ^= 0xFF  # flip a payload byte
    open(path, "wb").write(bytes(raw))
    with pytest.raises(ValueError, match="crc"):
        list(formats.read_tfrecord_file(path))


def test_webdataset_roundtrip(cluster, tmp_path):
    ds = rdata.from_items([
        {"__key__": f"s{i}", "txt": f"caption {i}".encode(),
         "cls": str(i).encode()}
        for i in range(6)
    ], parallelism=2)
    out = ds.write_webdataset(str(tmp_path / "wds"))
    assert out and all(p.endswith(".tar") for p in out)

    back = rdata.read_webdataset(str(tmp_path / "wds")).materialize()
    rows = sorted(back.take_all(), key=lambda r: r["__key__"])
    assert [r["__key__"] for r in rows] == [f"s{i}" for i in range(6)]
    assert rows[2]["txt"] == b"caption 2"
    assert rows[2]["cls"] == b"2"


def _write_avro(path, schema: dict, rows, codec=b"null"):
    """Hand-rolled avro writer (tests only; the library reader is the
    product surface)."""
    def zig(n):
        return _varint((n << 1) ^ (n >> 63))

    def _varint(n):
        out = b""
        n &= (1 << 64) - 1
        while True:
            b = n & 0x7F
            n >>= 7
            if n:
                out += bytes([b | 0x80])
            else:
                return out + bytes([b])

    def enc(schema, v):
        if isinstance(schema, dict) and schema["type"] == "record":
            return b"".join(enc(f["type"], v[f["name"]])
                            for f in schema["fields"])
        if isinstance(schema, list):  # union: pick the matching branch
            idx = 0 if v is None else 1
            return zig(idx) + (b"" if v is None else enc(schema[idx], v))
        if schema in ("int", "long"):
            return zig(v)
        if schema == "double":
            return struct.pack("<d", v)
        if schema == "string":
            b = v.encode()
            return zig(len(b)) + b
        raise AssertionError(schema)

    body = b"".join(enc(schema, r) for r in rows)
    if codec == b"deflate":
        cobj = zlib.compressobj(wbits=-15)
        body = cobj.compress(body) + cobj.flush()
    sync = bytes(range(16))
    meta = {"avro.schema": json.dumps(schema).encode(), "avro.codec": codec}
    out = io.BytesIO()
    out.write(b"Obj\x01")
    out.write(zig(len(meta)))
    for k, v in meta.items():
        kb = k.encode()
        out.write(zig(len(kb)) + kb + zig(len(v)) + v)
    out.write(zig(0))
    out.write(sync)
    out.write(zig(len(rows)) + zig(len(body)) + body + sync)
    with open(path, "wb") as f:
        f.write(out.getvalue())


AVRO_SCHEMA = {
    "type": "record", "name": "Rec",
    "fields": [
        {"name": "id", "type": "long"},
        {"name": "score", "type": "double"},
        {"name": "tag", "type": "string"},
        {"name": "opt", "type": ["null", "long"]},
    ],
}


@pytest.mark.parametrize("codec", [b"null", b"deflate"])
def test_avro_decoding(tmp_path, codec, cluster):
    rows = [{"id": i, "score": i * 0.5, "tag": f"t{i}",
             "opt": None if i % 2 else i * 10}
            for i in range(7)]
    path = str(tmp_path / "data.avro")
    _write_avro(path, AVRO_SCHEMA, rows, codec=codec)

    decoded = formats.read_avro_file(path)
    assert decoded == rows

    ds = rdata.read_avro(path).materialize()
    got = sorted(ds.take_all(), key=lambda r: r["id"])
    assert [r["tag"] for r in got] == [f"t{i}" for i in range(7)]


def test_from_torch(cluster):
    import torch

    class DS(torch.utils.data.Dataset):
        def __len__(self):
            return 5

        def __getitem__(self, i):
            return {"x": i, "y": i * i}

    ds = rdata.from_torch(DS())
    rows = sorted(ds.materialize().take_all(), key=lambda r: r["x"])
    assert [r["y"] for r in rows] == [0, 1, 4, 9, 16]


def test_from_huggingface_via_pandas_protocol(cluster):
    import pandas as pd

    class FakeHF:  # anything exposing to_pandas (datasets.Dataset does)
        def to_pandas(self):
            return pd.DataFrame({"a": [1, 2, 3]})

    ds = rdata.from_huggingface(FakeHF())
    assert sorted(r["a"] for r in ds.materialize().take_all()) == [1, 2, 3]
