"""ViT model family: shapes, learning, and sharded execution on the
virtual CPU mesh (test model mirrors tests/test_model_llama.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ray_tpu.models import vit
from ray_tpu.parallel.mesh import MeshSpec, logical_spec, make_mesh


def test_forward_shapes_and_determinism():
    cfg = vit.tiny_config()
    params = vit.init_params(cfg, jax.random.PRNGKey(0))
    imgs = jax.random.uniform(jax.random.PRNGKey(1), (3, 32, 32, 3))
    logits = vit.forward(params, imgs, cfg)
    assert logits.shape == (3, 10)
    assert logits.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(logits),
                               np.asarray(vit.forward(params, imgs, cfg)),
                               rtol=1e-6)


def test_patchify_roundtrip_pixels():
    cfg = vit.tiny_config()
    imgs = jnp.arange(2 * 32 * 32 * 3, dtype=jnp.float32).reshape(
        2, 32, 32, 3)
    patches = vit.patchify(imgs, cfg)
    assert patches.shape == (2, 16, 8 * 8 * 3)
    # First patch = top-left 8x8 block, row-major.
    np.testing.assert_array_equal(
        np.asarray(patches[0, 0]).reshape(8, 8, 3),
        np.asarray(imgs[0, :8, :8, :]))


def test_param_axes_cover_params():
    cfg = vit.tiny_config()
    params = vit.init_params(cfg, jax.random.PRNGKey(0))
    axes = vit.param_logical_axes(cfg)
    flat_p = jax.tree_util.tree_leaves_with_path(params)
    flat_a = jax.tree_util.tree_leaves_with_path(
        axes, is_leaf=lambda x: isinstance(x, tuple))
    assert len(flat_p) == len(flat_a)
    for (pp, leaf), (ap, names) in zip(sorted(flat_p, key=str),
                                       sorted(flat_a, key=str)):
        assert str(pp) == str(ap)
        assert leaf.ndim == len(names), (pp, leaf.shape, names)


def test_vit_learns_toy_classes():
    """A tiny ViT separates two synthetic classes (bright vs dark) within
    a few jitted steps — the learning smoke gate for the family."""
    cfg = vit.tiny_config(num_classes=2)
    params = vit.init_params(cfg, jax.random.PRNGKey(0))
    tx = optax.adam(1e-3)
    opt = tx.init(params)

    @jax.jit
    def step(params, opt, imgs, labels):
        loss, grads = jax.value_and_grad(vit.loss_fn)(params, imgs,
                                                      labels, cfg)
        updates, opt = tx.update(grads, opt, params)
        return optax.apply_updates(params, updates), opt, loss

    rng = np.random.default_rng(0)
    labels = jnp.asarray(rng.integers(0, 2, 32).astype(np.int32))
    base = rng.uniform(0, 0.3, (32, 32, 32, 3)).astype(np.float32)
    imgs = jnp.asarray(base + 0.6 * np.asarray(labels)[:, None, None, None])
    first = None
    for _ in range(40):
        params, opt, loss = step(params, opt, imgs, labels)
        first = first if first is not None else float(loss)
    acc = float((jnp.argmax(vit.forward(params, imgs, cfg), -1)
                 == labels).mean())
    assert float(loss) < first
    assert acc >= 0.9, acc


def test_vit_sharded_train_step_8dev():
    """Jitted ViT train step over an fsdp=2 x tp=2 x dp=2 mesh with the
    logical-axis sharding rules — the multichip path for the family."""
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs the 8-device CPU mesh")
    cfg = vit.tiny_config(d_model=64, n_heads=4, d_ff=128)
    mesh = make_mesh(MeshSpec(dp=2, fsdp=2, tp=2), devs[:8])
    axes = vit.param_logical_axes(cfg)

    with mesh:
        params = vit.init_params(cfg, jax.random.PRNGKey(0))
        sharded = jax.tree_util.tree_map(
            lambda p, names: jax.device_put(
                p, jax.sharding.NamedSharding(mesh, logical_spec(names))),
            params, axes,
            is_leaf=lambda x: not isinstance(x, dict))
        imgs = jax.device_put(
            jnp.ones((8, 32, 32, 3), jnp.float32),
            jax.sharding.NamedSharding(
                mesh, logical_spec(("batch", None, None, None))))
        labels = jnp.zeros((8,), jnp.int32)

        @jax.jit
        def step(params, imgs, labels):
            loss, grads = jax.value_and_grad(vit.loss_fn)(params, imgs,
                                                          labels, cfg)
            return jax.tree_util.tree_map(
                lambda p, g: p - 1e-3 * g.astype(p.dtype), params, grads
            ), loss

        new_params, loss = step(sharded, imgs, labels)
        assert np.isfinite(float(loss))
        # Parameter shardings survive the update (no silent gather).
        assert (new_params["blocks"]["w_up"].sharding
                == sharded["blocks"]["w_up"].sharding)


def test_param_count_matches_pytree():
    cfg = vit.tiny_config()
    params = vit.init_params(cfg, jax.random.PRNGKey(0))
    actual = sum(int(np.prod(p.shape))
                 for p in jax.tree_util.tree_leaves(params))
    assert cfg.param_count() == actual
    big = vit.VIT_B_16
    # Spot-check the headline config against its formula inputs.
    assert abs(big.param_count() - 86_000_000) / 86e6 < 0.02
