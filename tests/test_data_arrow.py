"""Arrow block path: string/nested/null columns ride pyarrow Arrays
through the data plane — groupby/sort over a string-keyed parquet
dataset without numpy object arrays (reference analog:
python/ray/data/block.py:57 Arrow BlockAccessor backend).
"""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import ray_tpu
from ray_tpu import data as rdata
from ray_tpu.data.block import (BlockAccessor, col_take,
                                col_unique_inverse, is_arrow_col)


@pytest.fixture(scope="module")
def cluster():
    rt = ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield rt
    ray_tpu.shutdown()


@pytest.fixture()
def string_parquet(tmp_path):
    """Two parquet files with a string key, a nullable string, and a
    nested list column."""
    rng = np.random.default_rng(0)
    paths = []
    for i in range(2):
        n = 50
        table = pa.table({
            "city": pa.array(rng.choice(["osaka", "kyoto", "nara"], n)),
            "note": pa.array([None if j % 7 == 0 else f"n{j}"
                              for j in range(n)]),
            "tags": pa.array([["a", "b"][: 1 + j % 2] for j in range(n)]),
            "pop": rng.integers(1, 100, n).astype(np.int64),
        })
        p = str(tmp_path / f"part{i}.parquet")
        pq.write_table(table, p)
        paths.append(p)
    return paths


def test_reader_auto_selects_arrow_columns(cluster, string_parquet):
    ds = rdata.read_parquet(string_parquet)
    block = next(ds.iter_batches(batch_size=None))
    assert is_arrow_col(block["city"]), type(block["city"])
    assert is_arrow_col(block["note"])   # nullable -> arrow
    assert is_arrow_col(block["tags"])   # nested -> arrow
    assert isinstance(block["pop"], np.ndarray)  # numeric -> numpy
    assert block["pop"].dtype == np.int64
    # NO object arrays anywhere.
    for col in block.values():
        if isinstance(col, np.ndarray):
            assert col.dtype != object


def test_string_key_groupby_without_object_arrays(cluster, string_parquet):
    ds = rdata.read_parquet(string_parquet)
    out = ds.groupby("city").sum("pop").materialize()
    rows = {r["city"]: r["sum(pop)"] for r in out.take_all()}
    # Cross-check against a host-side computation.
    t = pa.concat_tables([pq.read_table(p) for p in string_parquet])
    expect = {}
    for city, pop in zip(t["city"].to_pylist(), t["pop"].to_pylist()):
        expect[city] = expect.get(city, 0) + pop
    assert rows == expect


def test_string_key_sort_global_order(cluster, string_parquet):
    ds = rdata.read_parquet(string_parquet)
    cities = [r["city"] for r in
              ds.sort("city").materialize().take_all()]
    assert cities == sorted(cities)
    assert len(cities) == 100
    desc = [r["city"] for r in
            ds.sort("city", descending=True).materialize().take_all()]
    assert desc == sorted(desc, reverse=True)


def test_null_keys_group_and_sort(cluster, tmp_path):
    table = pa.table({
        "k": pa.array(["b", None, "a", "b", None, "a", "a"]),
        "v": np.arange(7, dtype=np.float64),
    })
    p = str(tmp_path / "nulls.parquet")
    pq.write_table(table, p)
    ds = rdata.read_parquet(p)
    counts = {r["k"]: r["count()"] for r in
              ds.groupby("k").count().materialize().take_all()}
    assert counts == {"a": 3, "b": 2, None: 2}
    srt = [r["k"] for r in ds.sort("k").materialize().take_all()]
    assert srt[:5] == ["a", "a", "a", "b", "b"]
    assert srt[5:] == [None, None]  # nulls last


def test_arrow_roundtrip_through_object_store(cluster):
    """Arrow columns survive the shm object plane (pickle-5 out-of-band
    IPC buffers) bit-exactly."""
    col = pa.array(["alpha", None, "gamma"] * 100)
    ref = ray_tpu.put({"s": col, "x": np.arange(300)})
    out = ray_tpu.get(ref)
    assert is_arrow_col(out["s"])
    assert out["s"].equals(col)


def test_arrow_shuffle_and_map_groups(cluster, string_parquet):
    ds = rdata.read_parquet(string_parquet)
    shuffled = ds.random_shuffle(seed=7).materialize()
    assert sorted(r["pop"] for r in shuffled.take_all()) == sorted(
        r["pop"] for r in rdata.read_parquet(string_parquet).take_all())

    def biggest(group):
        idx = np.argsort(np.asarray(group["pop"]))[-1:]
        return {"city": col_take(group["city"], idx),
                "pop": np.asarray(group["pop"])[idx]}

    tops = (rdata.read_parquet(string_parquet)
            .groupby("city").map_groups(biggest).materialize().take_all())
    assert len(tops) == 3


def test_write_parquet_preserves_arrow_columns(cluster, string_parquet,
                                               tmp_path):
    ds = rdata.read_parquet(string_parquet)
    outdir = str(tmp_path / "out")
    ds.write_parquet(outdir)
    back = rdata.read_parquet(outdir)
    assert sorted(r["city"] for r in back.take_all()) == sorted(
        r["city"] for r in ds.take_all())


def test_nullable_numeric_column_stays_numpy_nan(cluster, tmp_path):
    """Nullable ints/floats keep the numpy NaN representation so numeric
    consumers (aggregation, device_put) are unaffected, and sorts stay
    NUMERIC (never lexicographic)."""
    table = pa.table({
        "k": pa.array([10, 2, None, 7, 1], type=pa.int64()),
        "v": np.arange(5, dtype=np.float64),
    })
    p = str(tmp_path / "nn.parquet")
    pq.write_table(table, p)
    ds = rdata.read_parquet(p)
    block = next(ds.iter_batches(batch_size=None))
    assert isinstance(block["k"], np.ndarray)
    assert block["k"].dtype == np.float64  # NaN-filled
    srt = [r["k"] for r in ds.sort("k").materialize().take_all()]
    assert srt[:4] == [1.0, 2.0, 7.0, 10.0]  # numeric, not "10"<"2"


def test_sort_boundary_width_no_truncation(cluster, tmp_path):
    """String range boundaries must not be truncated to a block's max
    string width (searchsorted promotes widths itself)."""
    t1 = pa.table({"k": pa.array(["ban", "bag", "a"] * 10)})
    t2 = pa.table({"k": pa.array(["banana", "bananas", "zed"] * 10)})
    p1, p2 = str(tmp_path / "w1.parquet"), str(tmp_path / "w2.parquet")
    pq.write_table(t1, p1)
    pq.write_table(t2, p2)
    srt = [r["k"] for r in rdata.read_parquet([p1, p2])
           .sort("k", num_partitions=4).materialize().take_all()]
    assert srt == sorted(srt)


def test_json_csv_tfrecords_sinks_accept_arrow(cluster, string_parquet,
                                               tmp_path):
    ds = rdata.read_parquet(string_parquet, columns=["city", "pop"])
    jdir = str(tmp_path / "j")
    ds.write_json(jdir)
    back = rdata.read_json(jdir)
    assert sorted(r["city"] for r in back.take_all()) == sorted(
        r["city"] for r in ds.take_all())
    ds.write_csv(str(tmp_path / "c"))
    ds.write_tfrecords(str(tmp_path / "t"))


def test_col_unique_inverse_matches_numpy_semantics():
    col = pa.array(["b", "a", "c", "a", "b"])
    uniq, inv = col_unique_inverse(col)
    assert uniq.to_pylist() == ["a", "b", "c"]
    assert col.take(np.flatnonzero(inv == 0)).to_pylist() == ["a", "a"]
    n_uniq, n_inv = col_unique_inverse(np.array(["b", "a", "c", "a", "b"]))
    assert list(n_inv) == list(inv)
