"""Test fixtures.

JAX tests run on a virtual 8-device CPU mesh (the reference's analog is the
fake multi-node cluster in python/ray/cluster_utils.py + mocked accelerator
detection in tests/accelerators/test_tpu.py): real TPU hardware is never
required for the suite.
"""

import os

# Must be set before jax (imported transitively) initializes its backend.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("RTPU_TPU_CHIPS", "0")

import jax  # noqa: E402

# The axon TPU plugin force-appends itself to jax_platforms at import time,
# which silently puts "CPU" tests on the real chip (nondeterministic bf16
# matmuls). Pin the platform list before any backend is initialized.
jax.config.update("jax_platforms", "cpu")

import glob  # noqa: E402

import pytest  # noqa: E402

# Reap object-store segments leaked by SIGKILL'd clusters of previous
# runs — but ONLY segments no live process has mapped: a concurrently
# running cluster (e.g. a benchmark capture on the same host) must not
# lose its store to a test session starting next to it.
def _mapped_segments() -> set:
    mapped = set()
    for _pid in os.listdir("/proc"):
        if not _pid.isdigit():
            continue
        try:
            with open(f"/proc/{_pid}/maps") as _f:
                for _line in _f:
                    if "/dev/shm/rtpu_store_" in _line:
                        mapped.add(_line.rsplit("/", 1)[-1].strip())
        except OSError:
            continue
    return mapped


_live = _mapped_segments()
for _stale in glob.glob("/dev/shm/rtpu_store_*"):
    if os.path.basename(_stale) in _live:
        continue
    try:
        os.unlink(_stale)
    except OSError:
        pass


def pytest_configure(config):
    # Tier-1 CI runs `-m 'not slow'` (ROADMAP): long sweeps opt out of
    # the 870s budget with this marker and run in the full suite only.
    config.addinivalue_line(
        "markers", "slow: long-running sweep excluded from tier-1")


@pytest.fixture
def local_init():
    import ray_tpu

    ray_tpu.init(local_mode=True)
    yield
    ray_tpu.shutdown()


@pytest.fixture
def cluster_init():
    import ray_tpu

    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


@pytest.fixture
def cpu_mesh8():
    import jax

    devices = jax.devices("cpu")
    assert len(devices) >= 8, "conftest must force 8 host devices"
    yield devices[:8]
