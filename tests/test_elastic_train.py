"""Elastic training: shrink-to-fit + grow-on-capacity with checkpoint
continuity (reference analog: train/v2 elastic scaling policy tests —
ScalingPolicy/ResizeDecision + controller resize).
"""

import json
import os
import tempfile
import time

import pytest

import ray_tpu
from ray_tpu.train import (Checkpoint, CheckpointConfig, FailureConfig,
                           JaxTrainer, RunConfig, ScalingConfig)


@pytest.fixture
def small_cluster():
    rt = ray_tpu.init(num_cpus=2)
    yield rt
    ray_tpu.shutdown()


def _counting_loop(config):
    """Checkpoints a step counter each round; reports world size so the
    test can observe the resize, with start_step proving continuity."""
    import ray_tpu.train as train

    ctx = train.get_context()
    start_step = 0
    ckpt = train.get_checkpoint()
    if ckpt is not None:
        with ckpt.as_directory() as d:
            with open(os.path.join(d, "state.json")) as f:
                start_step = json.load(f)["step"] + 1
    for step in range(start_step, config["num_steps"]):
        time.sleep(config.get("round_s", 0.2))
        payload = {"step": step, "start_step": start_step,
                   "world_size": ctx.get_world_size()}
        if ctx.get_world_rank() == 0:
            d = tempfile.mkdtemp(prefix="rtpu_elastic_ckpt_")
            with open(os.path.join(d, "state.json"), "w") as f:
                json.dump({"step": step}, f)
            train.report(payload, checkpoint=Checkpoint(d))
        else:
            train.report(payload)


def test_elastic_starts_degraded_then_grows(small_cluster, tmp_path):
    """2-CPU cluster, num_workers=4/min_workers=2: trains at world size 2;
    when a 4-CPU node joins, the gang resizes to 4 and resumes from the
    latest checkpoint (steps continue, never reset)."""
    import threading

    run = RunConfig(name="elastic", storage_path=str(tmp_path),
                    checkpoint_config=CheckpointConfig(num_to_keep=2),
                    failure_config=FailureConfig(max_failures=2))
    trainer = JaxTrainer(
        _counting_loop,
        train_loop_config={"num_steps": 40, "round_s": 0.25},
        scaling_config=ScalingConfig(num_workers=4, min_workers=2,
                                     cpus_per_worker=1.0),
        run_config=run,
    )

    done = threading.Event()

    def add_capacity():
        time.sleep(4.0)
        small_cluster.add_node(num_cpus=4)
        # PDEATHSIG is delivered when the SPAWNING THREAD exits (the
        # node_manager spawns workers from a dedicated thread for the same
        # reason): stay alive until fit() finishes.
        done.wait(300)

    adder = threading.Thread(target=add_capacity, daemon=True)
    adder.start()
    try:
        result = trainer.fit()
    finally:
        done.set()

    assert result.error is None, result.error
    sizes = [m["world_size"] for m in result.metrics_dataframe]
    assert sizes[0] == 2, f"should start degraded at 2, got {sizes[0]}"
    assert 4 in sizes, f"never grew to 4: {sorted(set(sizes))}"
    # Monotonic world size (grow only in this scenario).
    grew_at = sizes.index(4)
    assert all(s == 2 for s in sizes[:grew_at])
    assert all(s == 4 for s in sizes[grew_at:])
    # Continuity: the resized run RESUMED (started from a checkpoint,
    # not step 0), and the final step completed.
    resumed = [m for m in result.metrics_dataframe if m["world_size"] == 4]
    assert resumed[0]["start_step"] > 0
    assert result.metrics["step"] == 39
    # Steps never regress across the resize boundary.
    steps = [m["step"] for m in result.metrics_dataframe]
    assert all(b >= a for a, b in zip(steps, steps[1:]))


def test_fixed_size_unchanged_semantics(small_cluster, tmp_path):
    """min_workers=None keeps the v1 fixed-gang behavior."""
    run = RunConfig(name="fixed", storage_path=str(tmp_path))
    trainer = JaxTrainer(
        _counting_loop,
        train_loop_config={"num_steps": 3, "round_s": 0.05},
        scaling_config=ScalingConfig(num_workers=2),
        run_config=run,
    )
    result = trainer.fit()
    assert result.error is None
    assert [m["world_size"] for m in result.metrics_dataframe] == [2, 2, 2]
