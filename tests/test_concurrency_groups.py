"""Actor concurrency groups + cluster-wide task events (reference test
model: python/ray/tests/test_concurrency_group.py and the GcsTaskManager
state-API tests)."""

import time

import pytest

import ray_tpu
from ray_tpu.util import state as state_api


@pytest.fixture(scope="module")
def cluster():
    rt = ray_tpu.init(num_cpus=4)
    yield rt
    ray_tpu.shutdown()


def test_concurrency_groups_isolate_saturation(cluster):
    """A saturated default group must not block methods in another group
    (reference: ConcurrencyGroupManager per-group executors)."""

    @ray_tpu.remote(num_cpus=0, concurrency_groups={"io": 2})
    class Worker:
        def __init__(self):
            self.events = []

        def slow_default(self):
            time.sleep(1.5)
            return "default-done"

        @ray_tpu.method(concurrency_group="io")
        def ping(self):
            return "pong"

    w = Worker.remote()
    assert ray_tpu.get(w.ping.remote(), timeout=30) == "pong"
    # Saturate the default group (max_concurrency=1) with a slow call...
    slow_ref = w.slow_default.remote()
    time.sleep(0.2)
    # ...the io group must still answer immediately.
    t0 = time.perf_counter()
    assert ray_tpu.get(w.ping.remote(), timeout=30) == "pong"
    io_latency = time.perf_counter() - t0
    assert io_latency < 1.0, f"io group blocked behind default: {io_latency}"
    assert ray_tpu.get(slow_ref, timeout=30) == "default-done"
    ray_tpu.kill(w)


def test_concurrency_group_parallelism_capped(cluster):
    """A group of size 2 runs at most 2 of its methods concurrently."""

    @ray_tpu.remote(num_cpus=0, concurrency_groups={"g": 2},
                    max_concurrency=4)
    class Capped:
        def __init__(self):
            import threading

            self._active = 0
            self._peak = 0
            self._lock = threading.Lock()

        @ray_tpu.method(concurrency_group="g")
        def work(self):
            with self._lock:
                self._active += 1
                self._peak = max(self._peak, self._active)
            time.sleep(0.3)
            with self._lock:
                self._active -= 1
            return True

        def peak(self):
            return self._peak

    c = Capped.remote()
    ray_tpu.get([c.work.remote() for _ in range(6)], timeout=60)
    peak = ray_tpu.get(c.peak.remote(), timeout=30)
    assert peak == 2, peak
    ray_tpu.kill(c)


def test_size_one_group_preserves_order(cluster):
    @ray_tpu.remote(num_cpus=0, concurrency_groups={"ordered": 1},
                    max_concurrency=8)
    class Ordered:
        def __init__(self):
            self.log = []

        @ray_tpu.method(concurrency_group="ordered")
        def step(self, i):
            self.log.append(i)
            return i

        def get_log(self):
            return list(self.log)

    o = Ordered.remote()
    ray_tpu.get([o.step.remote(i) for i in range(20)], timeout=60)
    assert ray_tpu.get(o.get_log.remote(), timeout=30) == list(range(20))
    ray_tpu.kill(o)


def test_list_tasks_sees_other_owners_tasks(cluster):
    """Tasks submitted INSIDE a worker (a different owner than this
    driver) must appear in the driver's list_tasks via the head's
    aggregated event ring (the VERDICT 'driver B sees driver A's tasks'
    criterion)."""

    @ray_tpu.remote
    def inner_task_xyz():
        return 1

    @ray_tpu.remote
    def submitter():
        # This worker OWNS these submissions; the driver does not.
        return sum(ray_tpu.get([inner_task_xyz.remote()
                                for _ in range(5)]))

    assert ray_tpu.get(submitter.remote(), timeout=60) == 5
    deadline = time.time() + 15
    seen = False
    while time.time() < deadline and not seen:
        tasks = state_api.list_tasks(limit=500)
        names = [t.get("name", "") for t in tasks
                 if t.get("state") == "FINISHED"]
        seen = any("inner_task_xyz" in n for n in names)
        if not seen:
            time.sleep(0.5)
    assert seen, "other owner's tasks never reached the head ring"
    # Owner attribution present on aggregated events.
    ev = [t for t in tasks if "inner_task_xyz" in t.get("name", "")][0]
    assert ev.get("owner"), ev
