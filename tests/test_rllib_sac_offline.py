"""SAC + offline RL (BC, CQL) learning gates (reference test model:
rllib tuned_examples regression gates for sac/pendulum and
bc/cql cartpole offline suites)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib import (BCConfig, CQLConfig, OfflineData, SACConfig,
                           SACLearner)
from ray_tpu.rllib.env import CartPoleVecEnv, PendulumVecEnv


@pytest.fixture(scope="module")
def cluster():
    rt = ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield rt
    ray_tpu.shutdown()


# ------------------------------------------------------------------- env

def test_pendulum_env_contract():
    env = PendulumVecEnv(num_envs=4, seed=0)
    obs = env.reset()
    assert obs.shape == (4, 3)
    # cos^2 + sin^2 = 1 for every row
    np.testing.assert_allclose(obs[:, 0] ** 2 + obs[:, 1] ** 2, 1.0,
                               atol=1e-5)
    for t in range(205):
        obs, r, done, info = env.step(
            np.zeros((4, 1), np.float32))
        assert r.shape == (4,) and (r <= 0).all()
    # 200-step truncation must have fired exactly once per env by now.
    assert info["truncated"].dtype == np.bool_


# --------------------------------------------------------------- learner

def test_sac_learner_updates_all_parts():
    rng = np.random.default_rng(0)
    learner = SACLearner(3, 1, seed=0, act_scale=2.0)
    batch = {
        "obs": rng.normal(size=(64, 3)).astype(np.float32),
        "actions": rng.uniform(-2, 2, (64, 1)).astype(np.float32),
        "rewards": rng.normal(size=64).astype(np.float32),
        "next_obs": rng.normal(size=(64, 3)).astype(np.float32),
        "dones": np.zeros(64, np.float32),
    }
    import jax

    before = jax.tree_util.tree_leaves(learner.state.actor)[0].copy()
    stats = learner.update_from_batch(batch)
    after = jax.tree_util.tree_leaves(learner.state.actor)[0]
    assert not np.allclose(before, after), "actor params did not move"
    for k in ("critic_loss", "actor_loss", "alpha", "entropy"):
        assert np.isfinite(stats[k]), stats


@pytest.mark.slow  # tier-1 budget relief (PR 12): 39.0s measured on a quiet box;
# learning gate — SAC loss/step math stays covered by faster tests
def test_sac_pendulum_learning_gate():
    """Learning-regression gate (VERDICT r4 item 7): swing-up return
    improves from random (~ -1200) to better than -700 within budget."""
    algo = (SACConfig()
            .environment("Pendulum")
            .env_runners(num_env_runners=0, num_envs_per_env_runner=8,
                         rollout_fragment_length=16)
            .training(actor_lr=3e-4, critic_lr=3e-4,
                      train_batch_size=128,
                      num_steps_sampled_before_learning_starts=500,
                      updates_per_iteration=48)
            .build())
    best = -1e9
    try:
        for _ in range(120):
            result = algo.train()
            ret = result["env_runners"]["episode_return_mean"]
            if ret is not None:
                best = max(best, ret)
            if best >= -700.0:
                break
        assert best >= -700.0, f"SAC failed to learn: best return {best}"
    finally:
        algo.stop()


# ----------------------------------------------------------- offline data

def _expert_cartpole_batches(n_steps: int = 1500, noise: float = 0.2,
                             seed: int = 0):
    """Scripted PD-controller expert with epsilon-noise: good actions
    with enough coverage for offline TD."""
    env = CartPoleVecEnv(num_envs=8, seed=seed)
    rng = np.random.default_rng(seed)
    obs = env.reset(seed=seed)
    batches = []
    for _ in range(n_steps):
        expert = (obs[:, 2] + 0.4 * obs[:, 3] > 0).astype(np.int32)
        rand = rng.integers(0, 2, len(expert)).astype(np.int32)
        a = np.where(rng.random(len(expert)) < noise, rand, expert)
        prev = obs
        obs, r, done, info = env.step(a)
        final_obs = info.get("final_obs", obs)
        next_obs = np.where(done[:, None], final_obs, obs)
        batches.append({
            "obs": prev, "actions": a, "rewards": r,
            "next_obs": next_obs,
            "dones": info["terminated"].astype(np.float32),
        })
    return batches


def test_offline_data_roundtrip(cluster):
    batches = _expert_cartpole_batches(n_steps=50)
    data = OfflineData.from_batches(batches)
    assert len(data) == 50 * 8
    rng = np.random.default_rng(0)
    s = data.sample(32, rng)
    assert s["obs"].shape == (32, 4)
    assert s["actions"].dtype in (np.int32, np.int64)
    # Epoch iteration covers the dataset.
    seen = sum(len(b["actions"])
               for b in data.iter_epochs(64, epochs=1))
    assert seen == (len(data) // 64) * 64


def test_offline_data_from_buffer_bridge(cluster):
    from ray_tpu.rllib import ReplayBuffer

    buf = ReplayBuffer(1000, obs_size=4)
    for b in _expert_cartpole_batches(n_steps=20):
        buf.add_batch(b["obs"], b["actions"], b["rewards"],
                      b["next_obs"], b["dones"])
    data = OfflineData.from_buffer(buf)
    assert len(data) == len(buf)


def test_bc_cartpole_learning_gate(cluster):
    """BC clones a noisy expert: greedy eval return far above random
    (~20) — the offline-BC regression gate."""
    data = OfflineData.from_batches(_expert_cartpole_batches())
    algo = (BCConfig()
            .environment("CartPole")
            .training(lr=3e-3, train_batch_size=256,
                      updates_per_iteration=150)
            .offline_data(data)
            .build())
    try:
        ret = -1e9
        for _ in range(6):
            result = algo.train()
            ret = algo.evaluate()["env_runners"]["episode_return_mean"]
            if ret >= 150.0:
                break
        assert ret >= 150.0, f"BC failed to clone the expert: {ret}"
        acc = result["learners"]["default_policy"]["action_accuracy"]
        assert acc > 0.7, acc
    finally:
        algo.stop()


def test_cql_cartpole_learning_gate(cluster):
    """CQL learns a policy from the same fixed dataset via conservative
    TD — the offline value-learning regression gate."""
    data = OfflineData.from_batches(_expert_cartpole_batches())
    algo = (CQLConfig()
            .environment("CartPole")
            .training(lr=1e-3, cql_alpha=1.0, train_batch_size=256,
                      target_network_update_freq=200,
                      updates_per_iteration=200)
            .offline_data(data)
            .build())
    try:
        ret = -1e9
        for _ in range(8):
            algo.train()
            ret = algo.evaluate()["env_runners"]["episode_return_mean"]
            if ret >= 150.0:
                break
        assert ret >= 150.0, f"CQL failed to learn offline: {ret}"
    finally:
        algo.stop()
