"""Compiled actor-DAG execution (SURVEY M5; reference test model:
python/ray/dag/tests/experimental/test_accelerated_dag.py).
"""

import time

import pytest

import ray_tpu
from ray_tpu.dag import InputNode, MultiOutputNode


# Logical CPUs: every test gangs up 2-3 actors that live for the module
# (handle-scope actor GC is a known gap — reference kills actors when the
# last handle dies).
@pytest.fixture(scope="module")
def cluster():
    rt = ray_tpu.init(num_cpus=24)
    yield rt
    ray_tpu.shutdown()


@ray_tpu.remote
class Adder:
    def __init__(self, bias=0):
        self.bias = bias
        self.calls = 0

    def add(self, x):
        self.calls += 1
        return x + self.bias

    def boom(self, x):
        raise ValueError("deliberate")

    def ncalls(self):
        return self.calls


def test_linear_pipeline(cluster):
    a = Adder.remote(bias=1)
    b = Adder.remote(bias=10)
    with InputNode() as inp:
        dag = b.add.bind(a.add.bind(inp))
    compiled = dag.experimental_compile()
    try:
        for i in range(10):
            assert compiled.execute(i).get() == i + 11
    finally:
        compiled.teardown()


def test_fan_out_multi_output(cluster):
    a = Adder.remote(bias=100)
    b = Adder.remote(bias=200)
    with InputNode() as inp:
        dag = MultiOutputNode([a.add.bind(inp), b.add.bind(inp)])
    compiled = dag.experimental_compile()
    try:
        out = compiled.execute(5).get()
        assert out == [105, 205]
    finally:
        compiled.teardown()


def test_pipelined_rounds_overlap(cluster):
    """Submitting several rounds before reading any must work (channel
    capacity pipelining)."""
    a = Adder.remote(bias=2)
    with InputNode() as inp:
        dag = a.add.bind(inp)
    compiled = dag.experimental_compile()
    try:
        refs = [compiled.execute(i) for i in range(6)]
        assert [r.get() for r in refs] == [i + 2 for i in range(6)]
    finally:
        compiled.teardown()


def test_error_propagates_to_driver(cluster):
    a = Adder.remote()
    b = Adder.remote(bias=1)
    with InputNode() as inp:
        dag = b.add.bind(a.boom.bind(inp))
    compiled = dag.experimental_compile()
    try:
        with pytest.raises(ValueError, match="deliberate"):
            compiled.execute(1).get()
        # The DAG survives an error round: next round still works...
        with pytest.raises(ValueError, match="deliberate"):
            compiled.execute(2).get()
    finally:
        compiled.teardown()


def test_actor_still_serves_normal_calls(cluster):
    a = Adder.remote(bias=3)
    with InputNode() as inp:
        dag = a.add.bind(inp)
    compiled = dag.experimental_compile()
    try:
        assert compiled.execute(1).get() == 4
        # Regular RPC path unaffected by the resident DAG loop.
        assert ray_tpu.get(a.ncalls.remote(), timeout=30) >= 1
    finally:
        compiled.teardown()


def test_dag_faster_than_rpc_per_call(cluster):
    """The whole point: a compiled round trip must beat two scheduled actor
    calls (channel hop vs RPC/scheduling)."""
    a = Adder.remote(bias=1)
    b = Adder.remote(bias=1)
    # RPC chain timing
    ray_tpu.get(b.add.remote(ray_tpu.get(a.add.remote(0))))  # warm
    t0 = time.perf_counter()
    n = 30
    for i in range(n):
        ray_tpu.get(b.add.remote(ray_tpu.get(a.add.remote(i))))
    rpc_dt = time.perf_counter() - t0

    with InputNode() as inp:
        dag = b.add.bind(a.add.bind(inp))
    compiled = dag.experimental_compile()
    try:
        compiled.execute(0).get()  # warm
        t0 = time.perf_counter()
        for i in range(n):
            compiled.execute(i).get()
        dag_dt = time.perf_counter() - t0
    finally:
        compiled.teardown()
    assert dag_dt < rpc_dt, (dag_dt, rpc_dt)


def test_cpu_communicator_ring(cluster):
    from ray_tpu.dag import CpuCommunicator

    comms = CpuCommunicator.create_group(3)

    @ray_tpu.remote
    class RingNode:
        def __init__(self, comm):
            self.comm = comm

        def exchange(self, value):
            nxt = (self.comm.rank() + 1) % self.comm.world_size()
            prv = (self.comm.rank() - 1) % self.comm.world_size()
            self.comm.send(value, nxt)
            return self.comm.recv(prv)

    nodes = [RingNode.remote(c) for c in comms]
    out = ray_tpu.get([n.exchange.remote(i) for i, n in enumerate(nodes)],
                      timeout=60)
    assert out == [2, 0, 1]  # each received its predecessor's value
