"""Tier-1 guard: the repo lints clean against its checked-in baseline,
across ALL FIVE rule families.

A NEW violation of any codified invariant — concurrency family (lock
order, blocking-under-lock, close-without-shutdown, banned jax<0.5 /
dashboard APIs, swallowed exceptions, unjoined daemon threads), jax
family (closure-captured-array-into-jit, donation-then-read,
host-sync-in-hot-path, unclamped-dynamic-update-slice,
pallas-shape-rules, rng-reinit-per-mesh), dist family
(unclassified-rpc-handler, retry-unsafe-call,
direct-notify-bypasses-outbox, serial-fanout-no-deadline,
wall-clock-deadline, missing-chaos-role), res family
(acquire-without-release, begin-without-commit,
unbounded-registry-growth, thread-without-stop, fd-leak-on-error), or
chan family (chan-cursor-publish-order, chan-spill-pin-unreleased,
chan-ack-before-consume, chan-raw-seq-send,
chan-register-without-unregister, chan-dial-without-liveness,
chan-blocking-op-no-deadline, chan-mutate-after-send) —
fails this test, the same check `python -m ray_tpu.devtools.lint` runs
standalone. After an intentional change, regenerate with
``python -m ray_tpu.devtools.lint --write-baseline`` (add
``--family X`` to touch only one family's section).
"""

from __future__ import annotations

from ray_tpu.devtools import lint

_FRESH_ALL = None


def _fresh(families=lint.FAMILIES):
    """New findings restricted to ``families``. ONE repo scan (all
    families — exactly what the CLI default runs) shared across the
    tests here: per-family filtering on the result is equivalent to a
    per-family run, and three full AST passes over the repo would
    triple this module's tier-1 cost."""
    global _FRESH_ALL
    if _FRESH_ALL is None:
        root, paths = lint.default_roots()
        findings = lint.lint_paths(paths, root, families=lint.FAMILIES)
        baseline = lint.load_baseline(lint.DEFAULT_BASELINE)
        _FRESH_ALL = lint.new_findings(findings, baseline)
    want = set(families)
    return [f for f in _FRESH_ALL
            if lint.RULE_FAMILY.get(f.rule, "concurrency") in want]


def test_repo_lints_clean_against_baseline():
    fresh = _fresh()
    assert not fresh, (
        "new rtpu-lint findings (fix, suppress inline, or "
        "--write-baseline):\n" + "\n".join(str(f) for f in fresh))


def test_repo_jax_family_clean_with_empty_baseline_section():
    """The jax family holds a stronger line than the concurrency one:
    its baseline section is EMPTY (every in-tree finding was fixed or
    justified inline), so any jax-rule finding anywhere in the repo is
    new debt. Keep it that way — fix or allow-comment, don't baseline."""
    fresh = _fresh(families=("jax",))
    assert not fresh, (
        "new jax-lint findings (fix or allow-comment with a one-line "
        "justification — the jax baseline section stays empty):\n"
        + "\n".join(str(f) for f in fresh))
    baseline = lint._read_baseline_json(lint.DEFAULT_BASELINE)
    assert baseline["families"]["jax"]["findings"] == {}


def test_repo_res_family_clean():
    """The res family holds the same strong line as jax and dist: its
    baseline section is EMPTY — every releasable handle is released on
    every path, every registry fed by a handler or loop has eviction
    evidence, every daemon thread stops on the teardown path, every fd
    survives its error paths. Resource lifetime is the single most
    re-found bug class across PRs 1-11 (the lease-table leak, the
    forever-pinned borrows, the _local_objects mirror, the unjoined
    threads): fix or allow-comment new findings, never baseline them —
    ROADMAP item 3's durable control plane is only trustworthy if its
    tables provably don't leak."""
    fresh = _fresh(families=("res",))
    assert not fresh, (
        "new res-lint findings (fix or allow-comment with a one-line "
        "justification — the res baseline section stays empty):\n"
        + "\n".join(str(f) for f in fresh))
    baseline = lint._read_baseline_json(lint.DEFAULT_BASELINE)
    assert baseline["families"]["res"]["findings"] == {}


def test_repo_dist_family_clean():
    """Like the jax family, the dist family holds the stronger line:
    its baseline section is EMPTY — every RPC handler is classified,
    every retry path deadline-bounded on a monotonic clock, every
    directory frame rides its outbox, every server has a chaos role.
    Any dist finding anywhere in the repo is new debt: fix it or
    allow-comment with justification, never baseline it (ROADMAP item
    3's replay/re-delivery semantics depend on this contract holding
    machine-checked, not hand-waved)."""
    fresh = _fresh(families=("dist",))
    assert not fresh, (
        "new dist-lint findings (fix or allow-comment with a one-line "
        "justification — the dist baseline section stays empty):\n"
        + "\n".join(str(f) for f in fresh))
    baseline = lint._read_baseline_json(lint.DEFAULT_BASELINE)
    assert baseline["families"]["dist"]["findings"] == {}


def test_repo_chan_family_clean():
    """The chan family holds the same strong line as jax/dist/res: its
    baseline section is EMPTY — ring writers publish after the fill,
    spill reclaims observe consumption, acks follow application
    consume, seqs route through the auto-seq facades, registrations
    have death-scrubs, dials have liveness branches, blocking channel
    ops carry deadlines, and sent buffers are never mutated in place.
    Every recent real data-plane bug (the PR 19 _spill_in race, peer
    seq inversions, credit stalls) lived in this layer: fix or
    allow-comment new findings, never baseline them. The dynamic half
    is chan_debug.py's RTPU_DEBUG_CHAN witness."""
    fresh = _fresh(families=("chan",))
    assert not fresh, (
        "new chan-lint findings (fix or allow-comment with a one-line "
        "justification — the chan baseline section stays empty):\n"
        + "\n".join(str(f) for f in fresh))
    baseline = lint._read_baseline_json(lint.DEFAULT_BASELINE)
    assert baseline["families"]["chan"]["findings"] == {}
