"""Tier-1 guard: the repo lints clean against its checked-in baseline,
across BOTH rule families.

A NEW violation of any codified invariant — concurrency family (lock
order, blocking-under-lock, close-without-shutdown, banned jax<0.5 /
dashboard APIs, swallowed exceptions, unjoined daemon threads) or jax
family (closure-captured-array-into-jit, donation-then-read,
host-sync-in-hot-path, unclamped-dynamic-update-slice,
pallas-shape-rules, rng-reinit-per-mesh) — fails this test, the same
check `python -m ray_tpu.devtools.lint` runs standalone. After an
intentional change, regenerate with
``python -m ray_tpu.devtools.lint --write-baseline`` (add
``--family X`` to touch only one family's section).
"""

from __future__ import annotations

from ray_tpu.devtools import lint


def _fresh(families=lint.FAMILIES):
    root, paths = lint.default_roots()
    findings = lint.lint_paths(paths, root, families=families)
    baseline = lint.load_baseline(lint.DEFAULT_BASELINE)
    return lint.new_findings(findings, baseline)


def test_repo_lints_clean_against_baseline():
    fresh = _fresh()
    assert not fresh, (
        "new rtpu-lint findings (fix, suppress inline, or "
        "--write-baseline):\n" + "\n".join(str(f) for f in fresh))


def test_repo_jax_family_clean_with_empty_baseline_section():
    """The jax family holds a stronger line than the concurrency one:
    its baseline section is EMPTY (every in-tree finding was fixed or
    justified inline), so any jax-rule finding anywhere in the repo is
    new debt. Keep it that way — fix or allow-comment, don't baseline."""
    fresh = _fresh(families=("jax",))
    assert not fresh, (
        "new jax-lint findings (fix or allow-comment with a one-line "
        "justification — the jax baseline section stays empty):\n"
        + "\n".join(str(f) for f in fresh))
    baseline = lint._read_baseline_json(lint.DEFAULT_BASELINE)
    assert baseline["families"]["jax"]["findings"] == {}
