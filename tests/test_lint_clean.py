"""Tier-1 guard: the repo lints clean against its checked-in baseline.

A NEW violation of any codified invariant (lock order, blocking-under-
lock, close-without-shutdown, banned jax<0.5 / dashboard APIs,
swallowed exceptions, unjoined daemon threads) fails this test — the
same check `python -m ray_tpu.devtools.lint` runs standalone. After an
intentional change, regenerate with
``python -m ray_tpu.devtools.lint --write-baseline``.
"""

from __future__ import annotations

from ray_tpu.devtools import lint


def _fresh():
    root, paths = lint.default_roots()
    findings = lint.lint_paths(paths, root)
    baseline = lint.load_baseline(lint.DEFAULT_BASELINE)
    return lint.new_findings(findings, baseline)


def test_repo_lints_clean_against_baseline():
    fresh = _fresh()
    assert not fresh, (
        "new rtpu-lint findings (fix, suppress inline, or "
        "--write-baseline):\n" + "\n".join(str(f) for f in fresh))
