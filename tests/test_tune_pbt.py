"""Tune PBT + experiment resume tests (reference analog:
python/ray/tune/tests/test_trial_scheduler_pbt.py + experiment_state).
"""

import json
import os
import tempfile

import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.train import Checkpoint, RunConfig


@pytest.fixture(scope="module")
def cluster():
    rt = ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield rt
    ray_tpu.shutdown()


def _moving_optimum_trainable(config):
    """Score = -(lr - target(t))^2: the best lr DRIFTS over time, so a
    static config loses and PBT's exploit+explore tracks it. State
    (cumulative score) rides checkpoints so exploits transfer progress."""
    score_sum = 0.0
    start = 0
    ckpt = tune.get_checkpoint()
    if ckpt is not None:
        with ckpt.as_directory() as d:
            with open(os.path.join(d, "state.json")) as f:
                st = json.load(f)
            score_sum, start = st["score_sum"], st["step"] + 1
    lr = config["lr"]
    for step in range(start, 16):
        target = 0.1 + 0.05 * step          # optimum drifts upward
        score_sum += -((lr - target) ** 2)
        d = tempfile.mkdtemp(prefix="pbt_ckpt_")
        with open(os.path.join(d, "state.json"), "w") as f:
            json.dump({"score_sum": score_sum, "step": step}, f)
        tune.report({"score": score_sum, "lr": lr, "step": step},
                    checkpoint=Checkpoint(d))


def test_pbt_beats_static_schedulers(cluster, tmp_path):
    """PBT's population tracks the moving optimum; the same population
    under FIFO (static configs) scores strictly worse."""

    def run(scheduler):
        tuner = tune.Tuner(
            _moving_optimum_trainable,
            param_space={"lr": tune.choice([0.05, 0.1, 0.3, 0.6])},
            tune_config=tune.TuneConfig(
                metric="score", mode="max", num_samples=4,
                max_concurrent_trials=4, seed=7, scheduler=scheduler),
            run_config=RunConfig(name=f"pbt-{id(scheduler)}",
                                 storage_path=str(tmp_path)),
        )
        grid = tuner.fit()
        assert not grid.errors, [r.error for r in grid.errors]
        return grid.get_best_result().metrics["score"]

    pbt_best = run(tune.PopulationBasedTraining(
        metric="score", mode="max", perturbation_interval=4,
        hyperparam_mutations={"lr": [0.05, 0.1, 0.3, 0.6, 0.9]},
        quantile_fraction=0.5, resample_probability=0.5, seed=7))
    fifo_best = run(tune.FIFOScheduler())
    assert pbt_best > fifo_best, (pbt_best, fifo_best)


def test_pbt_exploits_transfer_checkpoints(cluster, tmp_path):
    """A cloned trial resumes from the SOURCE's checkpoint: its history
    continues from the donor's cumulative state, not from step 0."""
    tuner = tune.Tuner(
        _moving_optimum_trainable,
        param_space={"lr": tune.grid_search([0.05, 0.9])},
        tune_config=tune.TuneConfig(
            metric="score", mode="max", max_concurrent_trials=2, seed=3,
            scheduler=tune.PopulationBasedTraining(
                metric="score", mode="max", perturbation_interval=4,
                hyperparam_mutations={"lr": [0.05, 0.3, 0.9]},
                quantile_fraction=0.5, seed=3)),
        run_config=RunConfig(name="pbt-clone", storage_path=str(tmp_path)),
    )
    grid = tuner.fit()
    assert not grid.errors
    # Every trial reached the final step either directly or post-clone.
    for r in grid:
        assert r.metrics["step"] == 15


def test_experiment_snapshot_and_restore(cluster, tmp_path):
    """Kill-and-restore: a snapshot taken mid-sweep restores every trial —
    finished ones keep results, unfinished ones resume from their latest
    checkpoint instead of restarting at step 0."""
    run_cfg = RunConfig(name="resumable", storage_path=str(tmp_path))
    tuner = tune.Tuner(
        _moving_optimum_trainable,
        param_space={"lr": tune.grid_search([0.1, 0.3])},
        tune_config=tune.TuneConfig(metric="score", mode="max",
                                    max_concurrent_trials=2),
        run_config=run_cfg,
    )
    grid = tuner.fit()
    assert not grid.errors
    exp_dir = os.path.join(str(tmp_path), "resumable")
    state_path = os.path.join(exp_dir, "experiment_state.json")
    assert os.path.exists(state_path)

    # Simulate an interruption: rewrite the snapshot so one trial looks
    # unfinished at step 7 with its checkpoint (what a mid-run kill -9
    # leaves behind), then restore.
    with open(state_path) as f:
        state = json.load(f)
    t0 = state["trials"][0]
    t0["done"] = False
    ckpt_at_7 = None
    # find the step-7 checkpoint from the trial's own reports
    d = tempfile.mkdtemp(prefix="pbt_ckpt_")
    with open(os.path.join(d, "state.json"), "w") as f:
        json.dump({"score_sum": -1.23, "step": 7}, f)
    t0["latest_checkpoint"] = d
    t0["history"] = t0["history"][:8]
    t0["iteration"] = 8
    with open(state_path, "w") as f:
        json.dump(state, f)

    restored = tune.Tuner.restore(exp_dir, _moving_optimum_trainable,
                                  tune_config=tune.TuneConfig(
                                      metric="score", mode="max",
                                      max_concurrent_trials=2),
                                  run_config=run_cfg)
    grid2 = restored.fit()
    assert not grid2.errors
    results = {r.trial_id: r for r in grid2}
    rt0 = results[t0["trial_id"]]
    # The resumed trial CONTINUED from the injected step-7 checkpoint:
    # first new report is step 8, cumulative score includes -1.23.
    new_reports = rt0.history[8:]
    assert new_reports[0]["step"] == 8
    assert rt0.metrics["step"] == 15
    # The other (finished) trial was not re-run.
    other = [r for r in grid2 if r.trial_id != t0["trial_id"]][0]
    assert other.metrics["step"] == 15
