"""Job submission + CLI tests (reference analog:
python/ray/tests/test_job_manager.py + dashboard job cli tests).
"""

import subprocess
import sys
import time

import pytest

import ray_tpu
from ray_tpu.jobs import JobStatus, JobSubmissionClient


@pytest.fixture(scope="module")
def cluster():
    rt = ray_tpu.init(num_cpus=4)
    yield rt
    ray_tpu.shutdown()


def test_job_submit_success_and_logs(cluster):
    client = JobSubmissionClient()
    job_id = client.submit_job(
        entrypoint=f"{sys.executable} -c \"print('job says hi')\"")
    status = client.wait_until_finish(job_id, timeout=120)
    assert status == JobStatus.SUCCEEDED
    assert "job says hi" in client.get_job_logs(job_id)
    infos = {j.submission_id: j for j in client.list_jobs()}
    assert infos[job_id].status == "SUCCEEDED"


def test_job_entrypoint_joins_cluster(cluster):
    """The submitted driver connects to THIS cluster via RTPU_ADDRESS and
    can run tasks on it."""
    script = (
        "import ray_tpu; ray_tpu.init();\n"
        "f = ray_tpu.remote(lambda: 21)\n"
        "print('answer', 2 * ray_tpu.get(f.remote(), timeout=60))\n")
    client = JobSubmissionClient()
    job_id = client.submit_job(
        entrypoint=f"{sys.executable} -c \"{script}\"")
    status = client.wait_until_finish(job_id, timeout=180)
    logs = client.get_job_logs(job_id)
    assert status == JobStatus.SUCCEEDED, logs
    assert "answer 42" in logs


def test_job_failure_and_runtime_env(cluster):
    client = JobSubmissionClient()
    bad = client.submit_job(entrypoint=f"{sys.executable} -c 'exit(3)'")
    assert client.wait_until_finish(bad, timeout=120) == JobStatus.FAILED
    assert "rc=3" in client.get_job_info(bad).message

    envd = client.submit_job(
        entrypoint=f"{sys.executable} -c \"import os; "
                   f"print('V=' + os.environ['JOBVAR'])\"",
        runtime_env={"env_vars": {"JOBVAR": "zap"}})
    assert client.wait_until_finish(envd, timeout=120) == JobStatus.SUCCEEDED
    assert "V=zap" in client.get_job_logs(envd)


def test_job_stop(cluster):
    client = JobSubmissionClient()
    job_id = client.submit_job(
        entrypoint=f"{sys.executable} -c 'import time; time.sleep(120)'")
    time.sleep(2.0)
    assert client.stop_job(job_id)
    status = client.wait_until_finish(job_id, timeout=60)
    assert status == JobStatus.STOPPED


def test_cli_status_and_submit(cluster):
    """Drive the CLI as a REAL subprocess against this live cluster."""
    addr = cluster.head_addr
    out = subprocess.run(
        [sys.executable, "-m", "ray_tpu.scripts.cli", "status",
         "--address", addr],
        capture_output=True, text=True, timeout=120, cwd="/root/repo")
    assert out.returncode == 0, out.stderr
    assert "alive" in out.stdout  # head node (+ the CLI driver node)
    assert "Resources:" in out.stdout

    out = subprocess.run(
        [sys.executable, "-m", "ray_tpu.scripts.cli", "submit",
         "--address", addr, "--timeout", "120", "--",
         sys.executable, "-c", "print('cli job ran')"],
        capture_output=True, text=True, timeout=180, cwd="/root/repo")
    assert out.returncode == 0, out.stderr + out.stdout
    assert "cli job ran" in out.stdout
    assert "SUCCEEDED" in out.stdout
