"""Model + sharding tests on the virtual 8-device CPU mesh (SURVEY.md §4.3:
the reference tests accelerator topology on CPU with mocked detection; here
the analog is an 8-device host-platform mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ray_tpu.models import llama
from ray_tpu.ops.attention import causal_attention
from ray_tpu.ops.ring_attention import ring_attention
from ray_tpu.parallel import spmd
from ray_tpu.parallel.mesh import MeshSpec, make_mesh


@pytest.fixture(scope="module")
def tiny_cfg():
    return llama.tiny_config()


def test_forward_shapes(tiny_cfg):
    params = llama.init_params(tiny_cfg, jax.random.key(0))
    tokens = jnp.zeros((2, 16), jnp.int32)
    logits = llama.forward(params, tokens, tiny_cfg)
    assert logits.shape == (2, 16, tiny_cfg.vocab_size)
    assert jnp.isfinite(logits).all()


def test_loss_decreases_with_training(tiny_cfg):
    key = jax.random.key(1)
    params = llama.init_params(tiny_cfg, key)
    tokens = jax.random.randint(key, (4, 32), 0, tiny_cfg.vocab_size)
    tx = optax.adam(1e-2)
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state):
        (loss, _), grads = jax.value_and_grad(llama.loss_fn, has_aux=True)(
            params, tokens, tiny_cfg)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    losses = []
    for _ in range(10):
        params, opt_state, loss = step(params, opt_state)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.9


def test_ring_attention_matches_dense(cpu_mesh8):
    """Ring attention over sp=8 must agree with single-device attention."""
    mesh = make_mesh(MeshSpec(sp=8), cpu_mesh8)
    b, s, h, d = 2, 64, 4, 16
    key = jax.random.key(0)
    q, k, v = (jax.random.normal(kk, (b, s, h, d), jnp.float32)
               for kk in jax.random.split(key, 3))
    pos = jnp.broadcast_to(jnp.arange(s), (b, s))
    ref = causal_attention(q, k, v, q_positions=pos, kv_positions=pos)
    out = jax.jit(lambda q, k, v: ring_attention(
        q, k, v, pos, pos, mesh=mesh, batch_spec=None, heads_axis=None))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_gqa_kv_cache_decode_matches_forward(tiny_cfg):
    """Prefill+decode against the KV cache must equal the full forward."""
    cfg = tiny_cfg
    params = llama.init_params(cfg, jax.random.key(2))
    tokens = jax.random.randint(jax.random.key(3), (2, 12), 0, cfg.vocab_size)
    full = llama.forward(params, tokens, cfg)

    cache = llama.init_kv_cache(cfg, 2, 16)
    logits_p, cache = llama.forward_with_cache(params, tokens[:, :8], cache, 0, cfg)
    np.testing.assert_allclose(np.asarray(logits_p), np.asarray(full[:, :8]),
                               rtol=2e-3, atol=2e-3)
    for i in range(8, 12):
        logits_d, cache = llama.forward_with_cache(
            params, tokens[:, i:i + 1], cache, i, cfg)
        np.testing.assert_allclose(np.asarray(logits_d[:, 0]),
                                   np.asarray(full[:, i]), rtol=2e-3, atol=2e-3)


def test_spmd_train_step_multichip(cpu_mesh8):
    """Full dp×fsdp×sp×tp train step compiles and runs on the 8-dev mesh."""
    mesh = make_mesh(MeshSpec(fsdp=2, sp=2, tp=2), cpu_mesh8)
    cfg = llama.tiny_config(n_heads=4, n_kv_heads=2, d_ff=128)
    tx = spmd.default_optimizer(lr=1e-3)
    state = spmd.sharded_init(cfg, mesh, jax.random.key(0), tx)
    step = spmd.make_train_step(cfg, mesh, tx)
    tokens = jax.device_put(
        jax.random.randint(jax.random.key(1), (4, 32), 0, cfg.vocab_size),
        spmd.data_sharding(mesh))
    state, metrics = step(state, tokens)
    state, metrics = step(state, tokens)
    assert int(state.step) == 2
    assert np.isfinite(float(metrics["loss"]))


def test_param_count_llama3_8b():
    assert abs(llama.LLAMA3_8B.param_count() - 8.03e9) / 8.03e9 < 0.01

@pytest.mark.slow  # tier-1 budget relief (PR 12): 24.1s measured on a quiet box;
# long-seq equivalence — short-seq blockwise equivalence stays tier-1
def test_long_seq_blockwise_and_chunked_ce_match_dense():
    """s=1024 exercises the production paths: blockwise online-softmax
    attention (sk>=1024) and lax.map-chunked cross-entropy (s > logits_chunk).
    Both must match the short-sequence dense implementations."""
    from ray_tpu.ops.attention import blockwise_attention

    cfg = llama.tiny_config(max_seq_len=1024)
    b, s = 2, 1024
    key = jax.random.key(7)
    params = llama.init_params(cfg, key)
    tokens = jax.random.randint(key, (b, s), 0, cfg.vocab_size)

    # Attention: blockwise vs dense, values and grads.
    h, d = 4, 16
    q, k, v = (jax.random.normal(kk, (b, s, h, d), jnp.float32)
               for kk in jax.random.split(key, 3))
    pos = jnp.broadcast_to(jnp.arange(s), (b, s))
    dense = causal_attention(q, k, v, q_positions=pos, kv_positions=pos)
    blk = blockwise_attention(q, k, v, q_positions=pos, kv_positions=pos)
    np.testing.assert_allclose(np.asarray(blk), np.asarray(dense),
                               rtol=2e-5, atol=2e-5)
    g_dense = jax.grad(lambda q: causal_attention(
        q, k, v, q_positions=pos, kv_positions=pos).sum())(q)
    g_blk = jax.grad(lambda q: blockwise_attention(
        q, k, v, q_positions=pos, kv_positions=pos).sum())(q)
    np.testing.assert_allclose(np.asarray(g_blk), np.asarray(g_dense),
                               rtol=2e-4, atol=2e-4)

    # Loss: chunked (512) vs unchunked (chunk >= s disables chunking).
    l_chunked, _ = llama.loss_fn(params, tokens, cfg, logits_chunk=512)
    l_dense, _ = llama.loss_fn(params, tokens, cfg, logits_chunk=s)
    np.testing.assert_allclose(float(l_chunked), float(l_dense),
                               rtol=1e-5, atol=1e-5)
    gc = jax.grad(lambda p: llama.loss_fn(p, tokens, cfg, logits_chunk=512)[0])(
        params)["blocks"]["wq"]
    gd = jax.grad(lambda p: llama.loss_fn(p, tokens, cfg, logits_chunk=s)[0])(
        params)["blocks"]["wq"]
    np.testing.assert_allclose(np.asarray(gc), np.asarray(gd),
                               rtol=2e-4, atol=2e-4)


def test_explicit_positions_route_position_masked_path():
    """forward(positions=arange) takes the explicit-position dispatch branch
    and must agree exactly with forward(positions=None) (fused-causal branch).
    Note position-based masking serves chunked prefill/decode; packed-document
    isolation needs segment ids (not yet supported)."""
    cfg = llama.tiny_config(max_seq_len=64)
    params = llama.init_params(cfg, jax.random.key(4))
    tokens = jax.random.randint(jax.random.key(5), (2, 64), 0, cfg.vocab_size)
    pos = jnp.broadcast_to(jnp.arange(64), (2, 64))
    np.testing.assert_allclose(
        np.asarray(llama.forward(params, tokens, cfg, positions=pos)),
        np.asarray(llama.forward(params, tokens, cfg)),
        rtol=1e-5, atol=1e-5)


def test_fused_kernel_gate_covers_llama_head_dims():
    """The TPU flash-kernel dispatch must engage for every Llama-family
    benchmarked config — round 1 shipped a gate requiring d % 128 == 0,
    which silently excluded head_dim=64 (Llama-1B) from the fused path."""
    from ray_tpu.models.llama import LLAMA3_1B, LLAMA3_8B, LLAMA3_70B
    from ray_tpu.ops.attention import use_fused_kernel

    for cfg in (LLAMA3_1B, LLAMA3_8B, LLAMA3_70B):
        assert use_fused_kernel(True, True, 2048, cfg.head_dim), cfg
    # Ragged/odd shapes still take the portable path.
    assert not use_fused_kernel(True, True, 2048 + 17, 64)
    assert not use_fused_kernel(True, True, 128, 64)      # too short
    assert not use_fused_kernel(True, False, 2048, 64)    # packed positions
    assert not use_fused_kernel(False, True, 2048, 64)    # CPU
    assert not use_fused_kernel(True, True, 2048, 192)    # unpadded mid dim
