"""Pull-manager unit tests over an in-process head + node managers.

Parity model: src/ray/object_manager/pull_manager.h behaviors — duplicate
concurrent pulls coalesce onto one in-flight transfer, large pulls fan
chunks out across multiple holders, and the directory orders holders
nearest-first (zone label) for the requester.
"""

import os
import threading
import time
import uuid

import pytest

from ray_tpu.core.config import GLOBAL_CONFIG as cfg
from ray_tpu.core.ids import ObjectID
from ray_tpu.cluster.head import HeadServer
from ray_tpu.cluster.node_manager import NodeManager


def _mk_node(head, zone: str) -> NodeManager:
    return NodeManager(head.address, uuid.uuid4().hex,
                       {"CPU": 1.0}, {"zone": zone}, 64 << 20)


@pytest.fixture
def mini_cluster():
    head = HeadServer()
    nodes = [_mk_node(head, z) for z in ("east", "west", "west")]
    yield head, nodes
    for n in nodes:
        n.shutdown()
    head.shutdown()


def _seal(head, nm: NodeManager, oid: ObjectID, data: bytes) -> None:
    mv = nm.store.create_buffer(oid, len(data))
    mv[:] = data
    nm.store.seal(oid)
    head.rpc_object_added(None, oid.binary(), nm.node_id, len(data))


def test_concurrent_pulls_coalesce_and_take_over(mini_cluster):
    """A second pull of an in-flight object waits on the first transfer
    (no duplicate stream); if the leader fails, a follower takes over."""
    head, (a, _b, c) = mini_cluster
    oid = ObjectID.from_random()
    data = os.urandom(1 << 20)
    _seal(head, a, oid, data)

    # Simulate an in-flight leader on c, then issue a concurrent pull:
    # it must COALESCE (wait) instead of opening a second transfer.
    ev = threading.Event()
    with c._pull_lock:
        c._pulls[oid.binary()] = ev
    results = []
    t = threading.Thread(target=lambda: results.append(
        c.rpc_pull_object(None, oid.binary(), 20000)), daemon=True)
    t.start()
    deadline = time.monotonic() + 5
    while (c.pull_stats["pulls_coalesced"] < 1
           and time.monotonic() < deadline):
        time.sleep(0.01)
    assert c.pull_stats["pulls_coalesced"] >= 1
    assert not c.store.contains(oid)  # still parked behind the "leader"
    # Leader "dies" without delivering: followers wake, one takes over.
    with c._pull_lock:
        c._pulls.pop(oid.binary(), None)
    ev.set()
    t.join(30)
    assert results == [True]
    assert c.store.contains(oid)
    # Exactly ONE transfer moved the bytes.
    assert c.pull_stats["bytes_pulled"] == len(data)

    buf = c.store.get(oid, timeout_ms=1000)
    assert bytes(buf.buffer) == data
    buf.release()


def test_multi_source_pull_fans_out_across_holders(mini_cluster):
    """A large object with several holders pulls chunks from multiple
    sources in parallel and reassembles correctly."""
    head, (a, b, c) = mini_cluster
    oid = ObjectID.from_random()
    data = os.urandom(6 << 20)
    _seal(head, a, oid, data)
    _seal(head, b, oid, data)
    old_chunk = cfg.object_transfer_chunk_bytes
    old_min = cfg.pull_fanout_min_bytes
    cfg.set("object_transfer_chunk_bytes", 1 << 20)
    cfg.set("pull_fanout_min_bytes", 2 << 20)
    try:
        assert c.rpc_pull_object(None, oid.binary(), 30000) is True
    finally:
        cfg.set("object_transfer_chunk_bytes", old_chunk)
        cfg.set("pull_fanout_min_bytes", old_min)
    assert c.pull_stats["multi_source_pulls"] == 1
    assert c.pull_stats["bytes_pulled"] == len(data)
    buf = c.store.get(oid, timeout_ms=1000)
    assert bytes(buf.buffer) == data
    buf.release()


def test_object_locations_orders_nearest_first(mini_cluster):
    """Holder list is sorted nearest-first for the requester: same-zone
    holders ahead of cross-zone ones."""
    head, (a, b, c) = mini_cluster  # zones: east, west, west
    oid = ObjectID.from_random()
    data = b"x" * 1024
    _seal(head, a, oid, data)
    _seal(head, b, oid, data)
    locs = head.rpc_object_locations(None, oid.binary(),
                                     requester_node_id=c.node_id)
    assert [nid for nid, _ in locs][0] == b.node_id  # west first for c
    locs_a = head.rpc_object_locations(None, oid.binary(),
                                       requester_node_id=a.node_id)
    assert [nid for nid, _ in locs_a][0] == a.node_id  # east first for a


def test_object_removed_drops_size_accounting(mini_cluster):
    head, (a, _b, _c) = mini_cluster
    oid = ObjectID.from_random()
    _seal(head, a, oid, b"y" * 2048)
    stats = head.rpc_scheduler_stats(None)
    assert stats["object_bytes_tracked"] >= 2048
    head.rpc_object_removed(None, oid.binary(), a.node_id)
    stats = head.rpc_scheduler_stats(None)
    assert oid.binary() not in head._object_sizes
