"""Diffusion (DDPM U-Net) family: shapes, schedule math, learning gate,
sampling, and sharded execution (mirrors the other model-family tests)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ray_tpu.models import diffusion
from ray_tpu.parallel.mesh import (MeshSpec, logical_spec, make_mesh,
                                   param_shardings)


def test_forward_shapes_and_determinism():
    cfg = diffusion.tiny_config()
    params = diffusion.init_params(cfg, jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 8, 1))
    t = jnp.asarray([3.0, 40.0])
    eps = diffusion.forward(params, x, t, cfg)
    assert eps.shape == (2, 8, 8, 1)
    np.testing.assert_allclose(
        np.asarray(eps), np.asarray(diffusion.forward(params, x, t, cfg)),
        rtol=1e-6)


def test_cosine_schedule_properties():
    cfg = diffusion.tiny_config(num_steps=100)
    s = diffusion.cosine_schedule(cfg)
    ab = np.asarray(s["alpha_bar"])
    assert ab.shape == (100,)
    assert np.all(np.diff(ab) <= 1e-9)       # monotone decreasing
    assert 0 < ab[-1] < ab[0] <= 1.0
    np.testing.assert_allclose(np.asarray(s["alphas"]),
                               1 - np.asarray(s["betas"]))


def test_param_axes_cover_params():
    cfg = diffusion.tiny_config()
    params = diffusion.init_params(cfg, jax.random.PRNGKey(0))
    axes = diffusion.param_logical_axes(cfg)
    flat_p = jax.tree_util.tree_leaves_with_path(params)
    flat_a = jax.tree_util.tree_leaves_with_path(
        axes, is_leaf=lambda x: isinstance(x, tuple))
    assert len(flat_p) == len(flat_a)
    for (pp, leaf), (ap, names) in zip(sorted(flat_p, key=str),
                                       sorted(flat_a, key=str)):
        assert str(pp) == str(ap)
        assert leaf.ndim == len(names), (pp, leaf.shape, names)


def test_param_count_matches_pytree():
    for cfg in (diffusion.tiny_config(),
                diffusion.tiny_config(widths=(16, 32, 64), image_size=16,
                                      channels=3)):
        params = diffusion.init_params(cfg, jax.random.PRNGKey(0))
        actual = sum(int(np.prod(p.shape))
                     for p in jax.tree_util.tree_leaves(params))
        assert cfg.param_count() == actual


def test_config_validation():
    with pytest.raises(ValueError, match="divisible"):
        diffusion.DiffusionConfig(image_size=30, widths=(16, 32, 64))
    with pytest.raises(ValueError, match="even"):
        diffusion.DiffusionConfig(time_dim=33)
    with pytest.raises(ValueError, match="norm_groups"):
        diffusion.DiffusionConfig(widths=(60, 128, 256), norm_groups=8)


@pytest.mark.slow  # tier-1 budget relief (PR 12): 32.5s measured on a quiet box;
# convergence smoke — forward/sharded-step coverage stays tier-1
def test_diffusion_learns_toy_distribution():
    """Learning gate: loss on a constant-image distribution drops well
    below the untrained level (eps-prediction becomes non-trivial)."""
    cfg = diffusion.tiny_config()
    params = diffusion.init_params(cfg, jax.random.PRNGKey(0))
    sched = diffusion.cosine_schedule(cfg)
    tx = optax.adam(2e-3)
    opt = tx.init(params)
    # Two-mode toy data: all +0.8 or all -0.8 images.
    rng = np.random.default_rng(0)
    signs = rng.choice([-0.8, 0.8], size=(64, 1, 1, 1))
    x0 = jnp.asarray(np.broadcast_to(signs, (64, 8, 8, 1)).astype(
        np.float32))

    @jax.jit
    def step(params, opt, key):
        (loss, _), grads = jax.value_and_grad(
            diffusion.loss_fn, has_aux=True)(params, x0, key, cfg, sched)
        updates, opt = tx.update(grads, opt, params)
        return optax.apply_updates(params, updates), opt, loss

    key = jax.random.PRNGKey(42)
    first = None
    for i in range(120):
        key, k = jax.random.split(key)
        params, opt, loss = step(params, opt, k)
        if first is None:
            first = float(loss)
    assert float(loss) < first * 0.55, (first, float(loss))

    # Sampling runs end-to-end with static shapes and finite output.
    out = diffusion.sample(params, jax.random.PRNGKey(7), cfg, batch=2,
                           schedule=sched)
    assert out.shape == (2, 8, 8, 1)
    assert np.isfinite(np.asarray(out)).all()


def test_diffusion_sharded_train_step_8dev():
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs the 8-device CPU mesh")
    cfg = diffusion.tiny_config()
    mesh = make_mesh(MeshSpec(dp=2, fsdp=2, tp=2), devs[:8])
    axes = diffusion.param_logical_axes(cfg)
    sched = diffusion.cosine_schedule(cfg)

    with mesh:
        params = diffusion.init_params(cfg, jax.random.PRNGKey(0))
        sharded = jax.tree_util.tree_map(
            jax.device_put, params, param_shardings(mesh, axes))
        x0 = jax.device_put(
            jnp.ones((8, 8, 8, 1), jnp.float32),
            jax.sharding.NamedSharding(
                mesh, logical_spec(("batch", None, None, None))))

        @jax.jit
        def step(params, x0, key):
            (loss, _), grads = jax.value_and_grad(
                diffusion.loss_fn, has_aux=True)(params, x0, key, cfg,
                                                 sched)
            return jax.tree_util.tree_map(
                lambda p, g: p - 1e-3 * g.astype(p.dtype), params, grads
            ), loss

        new_params, loss = step(sharded, x0, jax.random.PRNGKey(1))
        assert np.isfinite(float(loss))
        assert (new_params["mid"]["conv1"].sharding
                == sharded["mid"]["conv1"].sharding)
