"""Serve-path trace propagation: one request, one connected timeline.

Proxy admission -> router choice -> replica -> engine prefill + decode
chunks -> delivery, spec on/off, plus the tracing-off guarantee (no span
state anywhere on the request path).
"""

from __future__ import annotations

import json
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu.util import tracing

ENGINE_KW = {"max_batch": 2, "max_len": 64, "prompt_buckets": [8, 16],
             "decode_chunk": 2}


@pytest.fixture(scope="module")
def traced_serve():
    import os

    from ray_tpu.core.config import GLOBAL_CONFIG

    # controller + three single-replica deployments + the HTTP proxy
    # actor all need a CPU each.
    rt = ray_tpu.init(num_cpus=6, ignore_reinit_error=True,
                      _system_config={"tracing_enabled": True})
    yield rt
    from ray_tpu import serve

    serve.shutdown()
    ray_tpu.shutdown()
    GLOBAL_CONFIG.set("tracing_enabled", False)
    os.environ.pop("RTPU_TRACING_ENABLED", None)


def _deploy(name, **extra_engine_kw):
    from ray_tpu import serve
    from ray_tpu.serve.llm import build_llm_deployment

    kw = dict(ENGINE_KW, **extra_engine_kw)
    return serve.run(build_llm_deployment(name=name, num_replicas=1,
                                          engine_kwargs=kw), name=name)


def _trace_spans(trace_id, want_names, timeout=25):
    deadline = time.time() + timeout
    spans = []
    while time.time() < deadline:
        spans = tracing.get_trace(trace_id)
        if want_names <= {s["name"] for s in spans}:
            return spans
        time.sleep(0.4)
    return spans


def _assert_connected(spans, root_name):
    """Every span reaches the root by parent links within the trace."""
    by_id = {s["span_id"]: s for s in spans}
    for s in spans:
        hops = 0
        cur = s
        while cur["parent_id"]:
            cur = by_id.get(cur["parent_id"])
            assert cur is not None, \
                f"{s['name']} has a dangling parent chain"
            hops += 1
            assert hops < 20
        assert cur["name"] == root_name, (s["name"], cur["name"])


def test_handle_request_full_span_chain(traced_serve):
    """Route -> replica -> engine queued/prefill/decode chunks, one
    connected tree under the caller's root span."""
    h = _deploy("traced-llm")
    want = {"serve.route", "serve.replica:__call__", "engine.queued",
            "engine.prefill", "engine.decode_chunk"}
    with tracing.trace("req") as root:
        out = h.remote({"prompt_ids": [1, 2, 3, 4],
                        "max_new_tokens": 6}).result(timeout=180)
    assert out["num_generated"] == 6
    spans = _trace_spans(root.trace_id, want)
    names = {s["name"] for s in spans}
    assert want <= names, names
    _assert_connected(spans, "req")
    # Decode chunks carry per-request delivered-token counts that sum
    # (with prefill's first token) to the generation.
    chunk_toks = sum(s["attrs"]["tokens"] for s in spans
                     if s["name"] == "engine.decode_chunk")
    assert chunk_toks == 5  # prefill emits the first of 6
    route = next(s for s in spans if s["name"] == "serve.route")
    assert route["attrs"]["deployment"] == "traced-llm"
    assert "policy" in route["attrs"]
    prefill = next(s for s in spans if s["name"] == "engine.prefill")
    assert prefill["attrs"]["prefill_tokens"] == 4


def test_streaming_spec_on_span_chain(traced_serve):
    """Spec-on streaming request: same connected chain; decode-chunk
    spans carry the spec accept counts."""
    h = _deploy("traced-llm-spec", spec_draft_len=2, spec_chunk=2)
    prompt = [5, 6, 7, 5, 6, 7, 5, 6, 7, 5, 6]  # lookup-friendly
    want = {"serve.route", "serve.replica:stream", "engine.prefill",
            "engine.decode_chunk"}
    with tracing.trace("sreq") as root:
        toks = list(h.options("stream", stream=True).remote(
            {"prompt_ids": prompt, "max_new_tokens": 8}))
    assert len(toks) == 8
    spans = _trace_spans(root.trace_id, want)
    names = {s["name"] for s in spans}
    assert want <= names, names
    _assert_connected(spans, "sreq")
    spec_chunks = [s for s in spans if s["name"] == "engine.decode_chunk"
                   and s["attrs"].get("spec")]
    if spec_chunks:  # drafts proposed: accept counts must be reported
        assert all("spec_accepted" in s["attrs"] for s in spec_chunks)


def test_http_proxy_admission_to_delivery(traced_serve):
    """The ingress path: serve.request roots admission -> route ->
    replica -> engine -> delivery in ONE trace."""
    from ray_tpu import serve

    _deploy("traced-http")
    _proxy, port = serve.start_http()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/traced-http",
        data=json.dumps({"prompt_ids": [1, 2, 3],
                         "max_new_tokens": 4}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=180) as r:
        out = json.load(r)
    assert out["result"]["num_generated"] == 4
    # Find the request's trace via the head span tail.
    rt = traced_serve
    deadline = time.time() + 25
    trace_id = None
    while time.time() < deadline and trace_id is None:
        for s in rt.head.retrying_call("trace_tail", 5000, timeout=10):
            if s["name"] == "serve.request" and \
                    s["attrs"].get("deployment") == "traced-http":
                trace_id = s["trace_id"]
                break
        time.sleep(0.4)
    assert trace_id, "no serve.request span reached the head"
    want = {"serve.request", "serve.admission", "serve.route",
            "serve.replica:__call__", "engine.prefill",
            "engine.decode_chunk", "serve.delivery"}
    spans = _trace_spans(trace_id, want)
    assert want <= {s["name"] for s in spans}, {s["name"] for s in spans}
    _assert_connected(spans, "serve.request")


def test_tracing_off_request_path_is_span_free():
    """With tracing off: requests carry no trace context anywhere, the
    span buffer stays empty, and the engine's one-sync-per-chunk
    discipline is unchanged (the RTPU_DEBUG_JAX witness asserts the
    program/sync budget in tests/test_jax_debug.py; here we check the
    metric the witness counts)."""
    from ray_tpu.core.config import GLOBAL_CONFIG as cfg
    from ray_tpu.serve.llm import LLMEngine
    from ray_tpu.util.tracing import _buffer

    old = cfg.get("tracing_enabled")
    cfg.set("tracing_enabled", False)
    engine = LLMEngine(**ENGINE_KW)
    try:
        before = len(_buffer)
        req = engine._make_request([1, 2, 3, 4], 6, None)
        assert req.trace_ctx is None  # gates every engine span emit
        engine._queue.put(req)
        out = req.future.result(timeout=180)
        assert out["num_generated"] == 6
        assert len(_buffer) == before  # no span dict ever allocated
        snap = engine.stats()
        # 1 prefill sync + ceil(5/2) decode-chunk syncs.
        assert snap["decode_host_syncs"] == 3
    finally:
        engine.close()
        cfg.set("tracing_enabled", old)
