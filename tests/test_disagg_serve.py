"""Disaggregated prefill/decode serving: equivalence + failure tests.

Engine tier (store-free, tier-1): a prefill-role engine's KV handoff
installed into a decode-role engine must continue to TOKEN-IDENTICAL
greedy output vs the colocated engine, with the decode side's KV block
chain hashes equal to the prefill side's.

Serve tier (needs the native store lib, like every cluster-booting
test): ``build_llm_deployment(disaggregated=True)`` vs the colocated
deployment over real replicas + DAG channels, including decode-replica
death mid-service (the request re-routes, satellite-6 contract).
"""

import pytest


def _engine(**kw):
    from ray_tpu.serve.llm import LLMEngine

    base = dict(max_batch=2, max_len=96, prompt_buckets=[8, 16, 32],
                decode_chunk=4, seed=0)
    base.update(kw)
    return LLMEngine(**base)


PROMPTS = [
    [5, 9, 2, 7, 7, 1],
    [3, 3, 3, 3, 1, 2, 8, 4, 4, 4, 9, 9, 1, 0, 2, 5, 6, 7],
    list(range(1, 33)),  # multi-page prompt (block 16 -> 2 pages)
]


# ------------------------------------------------------------ engine tier


def test_disagg_token_identity_vs_colocated():
    colo = _engine()
    pre = _engine(role="prefill")
    dec = _engine(role="decode")
    try:
        for p in PROMPTS:
            ref = colo.generate(p, max_new_tokens=20)
            h = pre.prefill_remote(p, max_new_tokens=20)
            assert h.get("kv_handoff"), h
            out = dec.install_remote(h)
            assert out["token_ids"] == ref["token_ids"], p
    finally:
        colo.close()
        pre.close()
        dec.close()


def test_disagg_chain_hashes_equal_on_decode_side():
    pre = _engine(role="prefill")
    dec = _engine(role="decode")
    try:
        p = PROMPTS[2]
        h = pre.prefill_remote(p, max_new_tokens=4)
        assert len(h["chain"]) == len(p) // 16  # complete blocks hashed
        req = dec.install_async(h)
        req.future.result(timeout=120)
        # The install asserted chain equality internally; a corrupted
        # chain must be REJECTED (wrong-KV installs can't go silent).
        h2 = pre.prefill_remote(PROMPTS[1], max_new_tokens=4)
        h2["chain"] = [hash("corrupt")]
        with pytest.raises(RuntimeError, match="chain mismatch"):
            dec.install_remote(h2)
        # ...and the failed install released its slot.
        assert dec.kv.free_slots() == dec.max_batch
    finally:
        pre.close()
        dec.close()


def test_disagg_with_chunked_prefill_and_prefix_reuse():
    """Chunked prefill on the prefill engine + a repeat-prefix prompt
    (the prefill-side prefix cache serves the reused blocks) still
    hands off KV that decodes token-identically."""
    colo = _engine()
    pre = _engine(role="prefill", prefill_chunk=16)
    dec = _engine(role="decode")
    try:
        p = PROMPTS[2]
        for trip in range(2):  # second trip hits the prefill prefix cache
            ref = colo.generate(p, max_new_tokens=12)
            h = pre.prefill_remote(p, max_new_tokens=12)
            out = dec.install_remote(h)
            assert out["token_ids"] == ref["token_ids"], trip
        assert pre.kv.hits >= 1  # the reuse actually happened
    finally:
        colo.close()
        pre.close()
        dec.close()


def test_disagg_budget_one_completes_on_prefill_side():
    pre = _engine(role="prefill")
    try:
        out = pre.prefill_remote(PROMPTS[0], max_new_tokens=1)
        assert "kv_handoff" not in out
        assert out["num_generated"] == 1
    finally:
        pre.close()


def test_disagg_concurrent_installs_queue_for_slots():
    """More concurrent handoffs than decode slots: installs wait FIFO
    for recycled slots instead of failing."""
    import threading

    pre = _engine(role="prefill")
    dec = _engine(role="decode", max_batch=2)
    try:
        handoffs = [pre.prefill_remote(PROMPTS[i % 3], max_new_tokens=8)
                    for i in range(5)]
        outs = [None] * 5

        def run(i):
            outs[i] = dec.install_remote(handoffs[i], timeout=180)

        ts = [threading.Thread(target=run, args=(i,)) for i in range(5)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=180)
        colo = _engine()
        try:
            for i in range(5):
                ref = colo.generate(PROMPTS[i % 3], max_new_tokens=8)
                assert outs[i]["token_ids"] == ref["token_ids"], i
        finally:
            colo.close()
    finally:
        pre.close()
        dec.close()


def test_disagg_engines_close_balanced(monkeypatch):
    """RTPU_DEBUG_RES: a full prefill→handoff→install→decode round
    leaves no outstanding kv_spec reservations on either engine."""
    monkeypatch.setenv("RTPU_DEBUG_RES", "1")
    from ray_tpu.devtools import res_debug

    res_debug.reset()
    pre = _engine(role="prefill")
    dec = _engine(role="decode")
    h = pre.prefill_remote(PROMPTS[1], max_new_tokens=8)
    dec.install_remote(h)
    pre.close()
    dec.close()
    assert not res_debug.violations(), res_debug.violations()
    assert res_debug.outstanding("kv_spec").get("kv_spec", 0) == 0
    res_debug.reset()


def test_disagg_roles_reject_wrong_entrypoints():
    colo = _engine()
    try:
        with pytest.raises(RuntimeError, match="role='prefill'"):
            colo.prefill_remote(PROMPTS[0])
        with pytest.raises(RuntimeError, match="role='decode'"):
            colo.install_async({"page": 16})
    finally:
        colo.close()


def test_disagg_page_size_mismatch_rejected():
    pre = _engine(role="prefill", prefix_block=16)
    dec = _engine(role="decode", prefix_block=8)
    try:
        h = pre.prefill_remote(PROMPTS[1], max_new_tokens=4)
        with pytest.raises(ValueError, match="page size mismatch"):
            dec.install_async(h)
    finally:
        pre.close()
        dec.close()


# ------------------------------------------------------------- serve tier


def _cluster_or_skip():
    from ray_tpu.core import shm_store

    try:
        shm_store._load_lib()
    except OSError as e:
        pytest.skip(f"native store lib unavailable: {e}")


@pytest.fixture(scope="module")
def serve_cluster():
    _cluster_or_skip()
    import ray_tpu
    import ray_tpu.serve as serve

    rt = ray_tpu.init(num_cpus=24)
    yield rt
    serve.shutdown()
    ray_tpu.shutdown()


def _collect_stream(handle, payload, timeout=240.0):
    gen = handle.options("stream", stream=True).remote(payload)
    import time as _t

    deadline = _t.time() + timeout
    toks = []
    for t in gen:
        toks.append(int(t))
        assert _t.time() < deadline, "stream stalled"
    return toks


def test_serve_disagg_stream_token_identity(serve_cluster):
    """Disaggregated streaming (prefill-time first token + decode
    deltas over the reverse result channel) is token-identical to
    colocated streaming AND to the non-streaming result — including
    the multi-page prompt and a mid-stream EOS stop."""
    import ray_tpu.serve as serve
    from ray_tpu.serve.llm import build_llm_deployment

    ek = dict(max_batch=2, max_len=96, prompt_buckets=[8, 16, 32],
              decode_chunk=4, seed=0)
    colo = serve.run(build_llm_deployment(name="stcolo",
                                          engine_kwargs=ek))
    dis = serve.run(build_llm_deployment(
        name="stdis", disaggregated=True, num_decode_replicas=2,
        engine_kwargs=ek))
    for p in PROMPTS:
        req = {"prompt_ids": p, "max_new_tokens": 12}
        ref = colo.remote(dict(req)).result(timeout=120)["token_ids"]
        assert _collect_stream(colo, dict(req)) == ref, p
        assert _collect_stream(dis, dict(req)) == ref, p
    # Mid-stream EOS: pick a token the reference emits mid-generation
    # and make it the stop token — both streams must truncate there,
    # including the EOS token itself, identically.
    p = PROMPTS[2]
    ref = colo.remote({"prompt_ids": p, "max_new_tokens": 12}
                      ).result(timeout=120)["token_ids"]
    eos = ref[4]
    req = {"prompt_ids": p, "max_new_tokens": 12, "eos_id": eos}
    want = colo.remote(dict(req)).result(timeout=120)["token_ids"]
    assert want[-1] == eos and len(want) < len(ref)
    assert _collect_stream(colo, dict(req)) == want
    assert _collect_stream(dis, dict(req)) == want


def test_serve_disagg_stream_reroute_on_decode_death(serve_cluster):
    """SIGKILL the decode replicas after the stream has delivered a
    few tokens: the retained handoff re-routes to a (re-spawned or
    surviving) decode replica and the REPLAYED stream resumes where it
    left off — the consumer sees one token-identical sequence."""
    import ray_tpu
    import ray_tpu.serve as serve
    from ray_tpu.serve._private.controller import CONTROLLER_NAME
    from ray_tpu.serve.llm import build_llm_deployment

    ek = dict(max_batch=2, max_len=96, prompt_buckets=[8, 16, 32],
              decode_chunk=4, seed=0)
    colo = serve.run(build_llm_deployment(name="skcolo",
                                          engine_kwargs=ek))
    dis = serve.run(build_llm_deployment(
        name="skdis", disaggregated=True, num_decode_replicas=2,
        engine_kwargs=ek))
    p = PROMPTS[1]
    req = {"prompt_ids": p, "max_new_tokens": 16}
    ref = colo.remote(dict(req)).result(timeout=120)["token_ids"]
    gen = dis.options("stream", stream=True).remote(dict(req))
    got = []
    controller = ray_tpu.get_actor(CONTROLLER_NAME)
    for t in gen:
        got.append(int(t))
        if len(got) == 3:
            _, replicas = ray_tpu.get(
                controller.get_replica_set.remote("skdis-decode"),
                timeout=30)
            for rep in replicas:
                ray_tpu.kill(rep)
    assert got == ref


def test_serve_disagg_equivalence_and_reroute_on_death(serve_cluster):
    import ray_tpu
    import ray_tpu.serve as serve
    from ray_tpu.serve.llm import build_llm_deployment

    ek = dict(max_batch=2, max_len=96, prompt_buckets=[8, 16, 32],
              decode_chunk=4, seed=0)
    colo = serve.run(build_llm_deployment(name="eqcolo",
                                          engine_kwargs=ek))
    dis = serve.run(build_llm_deployment(
        name="eqdis", disaggregated=True, num_decode_replicas=2,
        engine_kwargs=ek))
    refs = {}
    for p in PROMPTS:
        refs[tuple(p)] = colo.remote(
            {"prompt_ids": p, "max_new_tokens": 12}).result(timeout=120)
        out = dis.remote(
            {"prompt_ids": p, "max_new_tokens": 12}).result(timeout=120)
        assert out["token_ids"] == refs[tuple(p)]["token_ids"], p

    # Kill ONE decode replica: channel edges to it die; in-flight and
    # later requests must re-route to the surviving replica and still
    # return token-identical results.
    from ray_tpu.serve._private.controller import CONTROLLER_NAME

    controller = ray_tpu.get_actor(CONTROLLER_NAME)
    version, replicas = ray_tpu.get(
        controller.get_replica_set.remote("eqdis-decode"), timeout=30)
    assert len(replicas) == 2
    ray_tpu.kill(replicas[0])
    for trip in range(3):
        for p in PROMPTS:
            out = dis.remote({"prompt_ids": p, "max_new_tokens": 12}
                             ).result(timeout=180)
            assert out["token_ids"] == refs[tuple(p)]["token_ids"], \
                (trip, p)
