"""RTPU_DEBUG_JAX runtime witness: recompile counting against declared
program budgets, the one-host-sync-per-chunk invariant (spec on/off,
int8 on/off), transfer-guard-clean engine ticks, and the zero-overhead
flag-off path.
"""

from __future__ import annotations

import numpy as np
import pytest

from ray_tpu.devtools import jax_debug

pytestmark = pytest.mark.filterwarnings("ignore::DeprecationWarning")


@pytest.fixture
def debug_jax(monkeypatch):
    monkeypatch.setenv("RTPU_DEBUG_JAX", "1")
    jax_debug.reset()
    yield
    jax_debug.reset()


# ------------------------------------------------------------ unit layer


def test_wrap_jit_passthrough_when_disabled(monkeypatch):
    monkeypatch.delenv("RTPU_DEBUG_JAX", raising=False)
    fn = object()
    assert jax_debug.wrap_jit(fn, "x") is fn
    # Sync notes are dict no-ops when off.
    jax_debug.note_host_sync("x")
    assert jax_debug.host_sync_counts() == {}


def test_recompile_witness_counts_and_budget(debug_jax):
    import jax

    f = jax_debug.wrap_jit(jax.jit(lambda x: x + 1), "t.f", budget=1)
    f(np.zeros(2, np.float32))
    f(np.ones(2, np.float32))          # same signature: cache hit
    assert f.program_count == 1
    assert jax_debug.over_budget_reports() == []
    f(np.zeros(3, np.float32))         # new shape: second program
    assert f.program_count == 2
    reports = jax_debug.over_budget_reports()
    assert len(reports) == 1
    assert reports[0]["name"] == "t.f" and reports[0]["budget"] == 1
    assert jax_debug.program_counts()["t.f"] == 2


def test_signature_tracks_dtype_and_structure(debug_jax):
    import jax

    f = jax_debug.wrap_jit(jax.jit(lambda t: t), "t.sig")
    f((np.zeros(2, np.float32),))
    f((np.zeros(2, np.int32),))            # dtype change
    f((np.zeros(2, np.float32), np.zeros(2, np.float32)))  # structure
    assert f.program_count == 3


def test_registry_does_not_pin_dead_witnesses(debug_jax):
    """The registry holds weakrefs: dropping a witness (engine close +
    GC) releases its trace cache and removes it from program_counts —
    a long debug session must not accumulate one program set per
    engine ever built."""
    import gc

    import jax

    f = jax_debug.wrap_jit(jax.jit(lambda x: x + 1), "t.dead")
    f(np.zeros(2, np.float32))
    assert jax_debug.program_counts()["t.dead"] == 1
    del f
    gc.collect()
    assert "t.dead" not in jax_debug.program_counts()


def test_host_sync_counter(debug_jax):
    jax_debug.note_host_sync("engine.decode")
    jax_debug.note_host_sync("engine.decode")
    jax_debug.note_host_sync("engine.prefill")
    assert jax_debug.host_sync_counts() == {"engine.decode": 2,
                                            "engine.prefill": 1}


def test_transfer_guard_disallow_blocks_implicit(debug_jax):
    import jax

    x = jax.device_put(np.ones(2, np.float32))
    with jax_debug.transfer_guard("disallow"):
        # Explicit placement/fetch is allowed...
        y = jax.device_put(np.zeros(2, np.float32))
        jax.device_get(jax.jit(lambda a, b: a + b)(x, y))
        # ...an implicit host operand is not.
        with pytest.raises(Exception, match="[Dd]isallowed"):
            jax.jit(lambda a, b: a + b)(x, np.zeros(2, np.float32))


def test_tick_guard_null_when_unconfigured(monkeypatch):
    monkeypatch.delenv("RTPU_DEBUG_JAX", raising=False)
    with jax_debug.tick_guard():
        pass  # null context
    monkeypatch.setenv("RTPU_DEBUG_JAX", "1")
    monkeypatch.delenv("RTPU_DEBUG_JAX_TRANSFER_GUARD", raising=False)
    with jax_debug.tick_guard():
        pass  # still null: no guard level requested


# ------------------------------------------------------- engine layer


def _engine(**kw):
    from ray_tpu.models import llama
    from ray_tpu.serve.engine.core import InferenceEngine

    cfg = llama.tiny_config(max_seq_len=256)
    kw.setdefault("max_batch", 2)
    kw.setdefault("max_len", 128)
    kw.setdefault("prompt_buckets", [16, 32])
    kw.setdefault("decode_chunk", 4)
    return InferenceEngine(cfg, **kw)


def _drive(eng, reps: int = 2):
    """Steady-state mix: two prompt lengths (both buckets), a
    repetitive prompt (so spec engines actually draft) and a varied
    one, repeated."""
    rng = np.random.default_rng(0)
    total = 0
    for _ in range(reps):
        total += eng.generate([7] * 12, max_new_tokens=16)[
            "num_generated"]
        total += eng.generate(
            [int(t) for t in rng.integers(1, 200, 24)],
            max_new_tokens=8)["num_generated"]
    return total


@pytest.mark.parametrize("workload", ["plain", "spec", "spec_int8"])
def test_steady_state_decode_programs_and_sync_cadence(debug_jax,
                                                       workload):
    """The acceptance sweep — one engine per workload (spec on/off,
    int8 on/off) asserts BOTH invariants at once:

    - the engine compiles EXACTLY its declared programs (one decode
      chunk program, one verify program iff speculation is on, one
      prefill program per prompt bucket used) and never recompiles in
      steady state;
    - every decode dispatch fetches the host EXACTLY once (witness
      decode-tag syncs == the per-chunk metric), and prefill once per
      admission.
    """
    kw = {}
    if workload != "plain":
        kw.update(spec_draft_len=4)
    if workload == "spec_int8":
        kw.update(quantize="int8")
    eng = _engine(**kw)
    try:
        assert _drive(eng) > 0
        first = eng.loop.program_counts()
        assert _drive(eng, reps=1) > 0      # steady state: no growth
        programs = eng.loop.program_counts()
        assert programs == first
        assert programs["decode_chunk"] == 1
        assert programs["prefill"] == 2     # both buckets exercised
        if workload == "plain":
            assert "verify_chunk" not in programs
        else:
            assert programs["verify_chunk"] == 1
        assert jax_debug.over_budget_reports() == []
        stats = eng.stats()
        assert stats["compiled_programs"] == programs
        # One host sync per decode chunk, exactly.
        syncs = jax_debug.host_sync_counts()
        assert stats["decode_host_syncs"] > 0
        assert syncs.get("engine.decode", 0) == \
            stats["decode_host_syncs"]
        # Prefill syncs once per admission (the first-token fetch).
        assert syncs.get("engine.prefill", 0) == stats["requests"]
        if workload != "plain":
            assert stats["spec_chunks"] > 0  # the verify path ran
    finally:
        eng.close()


def test_chunked_paged_engine_declared_schedule(debug_jax):
    """The chunked-prefill + paged-decode + multi-step engine keeps the
    SAME declared budgets: one decode program (paged dispatch is a
    static config branch inside it), prefill programs within the
    per-bucket budget even though a long prompt now dispatches MANY
    chunks (intermediate chunks reuse bucket shapes and fetch nothing),
    exactly one counted prefill sync per ADMISSION (the final chunk's
    first-token fetch), and decode witness syncs == the per-chunk
    metric (multi-step moves the fetch one chunk behind dispatch, it
    never adds or drops one)."""
    eng = _engine(prefill_chunk=16, paged_decode=True, prefix_block=16,
                  multi_step=True)
    try:
        # 40-token prompt -> chunks (16, 16, 8); short prompt -> one.
        out = eng.generate([3] * 40, max_new_tokens=12)
        assert out["num_generated"] == 12
        assert eng.generate([9, 8, 7], max_new_tokens=9)[
            "num_generated"] == 9
        first = eng.loop.program_counts()
        eng.generate([3] * 40, max_new_tokens=4)  # steady: no growth
        programs = eng.loop.program_counts()
        assert programs == first
        assert programs["decode_chunk"] == 1
        # Chunking NARROWS the prefill shape set: every full chunk is
        # the 16-token bucket and every tail (<= chunk) buckets back
        # into it — one program, under the 2-bucket budget.
        assert programs["prefill"] == 1
        assert jax_debug.over_budget_reports() == []
        stats = eng.stats()
        syncs = jax_debug.host_sync_counts()
        assert syncs.get("engine.decode", 0) == \
            stats["decode_host_syncs"]
        assert syncs.get("engine.prefill", 0) == stats["requests"] == 3
        # Chunked accounting: 40+3+40 real suffix tokens prefilled
        # (minus any warm prefix reuse on the repeat).
        assert stats["prefill_tokens"] + stats[
            "prefix_tokens_reused"] == 83
    finally:
        eng.close()


def test_transfer_guard_clean_engine_tick(debug_jax, monkeypatch):
    """Under RTPU_DEBUG_JAX_TRANSFER_GUARD=disallow every tick runs
    inside jax.transfer_guard: all device traffic must go through the
    explicit _put/_fetch pair. A stray implicit transfer raises in the
    engine thread and fails the roster — so a clean generate IS the
    assertion (spec path included)."""
    monkeypatch.setenv("RTPU_DEBUG_JAX_TRANSFER_GUARD", "disallow")
    eng = _engine(spec_draft_len=4)
    try:
        assert _drive(eng, reps=1) > 0
        assert jax_debug.host_sync_counts().get("engine.decode", 0) > 0
    finally:
        eng.close()


def test_flag_off_engine_is_unwrapped(monkeypatch):
    monkeypatch.delenv("RTPU_DEBUG_JAX", raising=False)
    eng = _engine()
    try:
        assert eng.loop.program_counts() == {}
        assert not isinstance(eng.loop.decode_chunk,
                              jax_debug.JitWitness)
        out = eng.generate([5, 6, 7], max_new_tokens=4)
        assert out["num_generated"] == 4
        assert "compiled_programs" not in eng.stats()
    finally:
        eng.close()


# ------------------------------------------------------- trainer layer


def test_train_step_single_program_budget(debug_jax):
    import jax
    import optax

    from ray_tpu.models import llama
    from ray_tpu.parallel import spmd
    from ray_tpu.parallel.mesh import MeshSpec, make_mesh, mesh_context

    cfg = llama.tiny_config(max_seq_len=64)
    mesh = make_mesh(MeshSpec(), jax.devices("cpu")[:1])
    tx = optax.sgd(1e-3)
    with mesh_context(mesh):
        state = spmd.sharded_init(cfg, mesh, jax.random.PRNGKey(0), tx)
        step = spmd.make_train_step(cfg, mesh, tx)
        tokens = np.zeros((2, 64), np.int32)
        for _ in range(3):
            state, metrics = step(state, jax.device_put(tokens))
        assert jax_debug.program_counts()["spmd.train_step"] == 1
        assert jax_debug.over_budget_reports() == []
        # A shape change is a SECOND program — over budget, reported.
        state, metrics = step(state, jax.device_put(
            np.zeros((4, 64), np.int32)))
        assert jax_debug.program_counts()["spmd.train_step"] == 2
        reports = jax_debug.over_budget_reports()
        assert [r["name"] for r in reports] == ["spmd.train_step"]
