"""Flight recorder: always-on per-process event ring, bounds, dumps.

Ring bounds + config resize, SIGUSR2 dump-to-file round trip, the
chaos-kill pre-dump hook, and the disabled path.
"""

from __future__ import annotations

import json
import os
import signal
import time

import pytest

from ray_tpu.core.config import GLOBAL_CONFIG as cfg
from ray_tpu.util import flight_recorder as fr


@pytest.fixture(autouse=True)
def _clean_ring():
    fr.clear()
    yield
    fr.clear()


def test_ring_bounded_by_config_size():
    old = cfg.get("flight_recorder_size")
    cfg.set("flight_recorder_size", 64)
    try:
        for i in range(500):
            fr.record("ev", i=i)
        events = fr.snapshot()
        assert len(events) == 64
        # Newest survive, oldest dropped.
        assert events[-1][2]["i"] == 499
        assert events[0][2]["i"] == 500 - 64
        # Shrinking the config re-sizes the live ring (keeps newest).
        cfg.set("flight_recorder_size", 16)
        fr.record("ev", i=500)
        assert len(fr.snapshot()) == 16
    finally:
        cfg.set("flight_recorder_size", old)


def test_disabled_records_nothing():
    old = cfg.get("flight_recorder_enabled")
    cfg.set("flight_recorder_enabled", False)
    try:
        fr.record("nope", x=1)
        assert all(e[1] != "nope" for e in fr.snapshot())
    finally:
        cfg.set("flight_recorder_enabled", old)


def test_event_shape_and_payload():
    fr.record("lease_grant", lease="abc", worker="1.2.3.4:5")
    ts, kind, fields = fr.snapshot()[-1]
    assert kind == "lease_grant"
    assert abs(ts - time.time()) < 5
    assert fields == {"lease": "abc", "worker": "1.2.3.4:5"}
    payload = fr.dump_payload(clock_offset_s=0.25)
    assert payload["pid"] == os.getpid()
    assert payload["clock_offset_s"] == 0.25
    assert payload["events"][-1][1] == "lease_grant"


def test_sigusr2_dump_round_trip(tmp_path):
    old_dir = cfg.get("flight_recorder_dump_dir")
    cfg.set("flight_recorder_dump_dir", str(tmp_path))
    try:
        assert fr.install_signal_handler()
        fr.record("pre_signal_marker", n=7)
        os.kill(os.getpid(), signal.SIGUSR2)
        deadline = time.time() + 10
        files = []
        while time.time() < deadline:
            time.sleep(0.05)  # the handler runs on the main thread here
            files = list(tmp_path.glob("flight-*.json"))
            if files:
                break
        assert files, "SIGUSR2 produced no dump file"
        payload = json.loads(files[0].read_text())
        assert payload["reason"] == "SIGUSR2"
        assert any(e[1] == "pre_signal_marker" and e[2] == {"n": 7}
                   for e in payload["events"])
    finally:
        cfg.set("flight_recorder_dump_dir", old_dir)
        signal.signal(signal.SIGUSR2, signal.SIG_DFL)


def test_chaos_kill_dumps_flight_ring(tmp_path, monkeypatch):
    """The chaos plan's kill action writes the ring to disk BEFORE the
    SIGKILL — the post-mortem the scenarios previously lost."""
    from ray_tpu.devtools import chaos

    killed = []
    monkeypatch.setattr(chaos, "_kill_self", lambda: killed.append(1))
    old_dir = cfg.get("flight_recorder_dump_dir")
    old_plan = cfg.get("chaos_plan")
    cfg.set("flight_recorder_dump_dir", str(tmp_path))
    cfg.set("chaos_plan", "kill:method=doomed_rpc:nth=1")
    try:
        fr.record("before_the_end", step=1)
        verdict = chaos.apply("head", "doomed_rpc", "request")
        assert killed and verdict == chaos.DROP
        files = list(tmp_path.glob("flight-*.json"))
        assert files, "chaos kill produced no flight dump"
        payload = json.loads(files[0].read_text())
        assert payload["reason"].startswith("chaos-kill:")
        assert any(e[1] == "before_the_end" for e in payload["events"])
    finally:
        cfg.set("chaos_plan", old_plan)
        cfg.set("flight_recorder_dump_dir", old_dir)


def test_cluster_dump_flight_rpc():
    """rpc_dump_flight on head + node returns live rings with identity
    (role/node_id) and the node's clock-offset estimate field."""
    import ray_tpu

    try:
        rt = ray_tpu.init(num_cpus=1, ignore_reinit_error=True)
    except RuntimeError as e:
        # Same env-failure set as the other cluster-booting tests: the
        # checked-in shm store lib may not load on this machine.
        pytest.skip(f"cluster unavailable here: {e}")
    try:
        head_dump = rt.head.retrying_call("dump_flight", timeout=10)
        assert head_dump["role"] == "head"
        assert head_dump["clock_offset_s"] == 0.0
        # Heartbeats + RPC dispatches must already be in SOME ring.
        deadline = time.time() + 15
        kinds: set = set()
        while time.time() < deadline:
            node_dump = rt.node.retrying_call("dump_flight", timeout=10)
            kinds = {e[1] for e in node_dump["events"]}
            if "hb" in kinds:  # first beat lands ~1 period after boot
                break
            time.sleep(0.3)
        assert node_dump["role"] == "node"
        assert node_dump["node_id"] == rt.node_id
        assert "hb" in kinds, kinds
        assert "clock_offset_s" in node_dump
        # clock_probe serves a wall time close to ours (same host).
        head_t = rt.head.retrying_call("clock_probe", timeout=10)
        assert abs(head_t - time.time()) < 5
    finally:
        ray_tpu.shutdown()
