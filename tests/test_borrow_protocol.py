"""Distributed borrow protocol: a borrowed ref passed through a nested
task on another node keeps the object alive until the borrower drops it.

Parity model: the reference's ReferenceCounter borrower bookkeeping
(reference_count.h WaitForRefRemoved protocol; python/ray/tests/
test_reference_counting.py's borrowed-ref cases). The transfer-pin TTL is
shortened so the test proves the BORROW REGISTRATION (not the pin) is
what keeps the object alive across the driver dropping its local ref.
"""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.core.config import GLOBAL_CONFIG as cfg
from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy


@pytest.fixture(scope="module")
def cluster2():
    # Short transfer pin: the owner-side serialization pin must expire
    # DURING the nested task, so only borrower registration can keep the
    # object alive (30s default would mask a broken protocol). Not TOO
    # short: the pin legitimately bridges the serialize -> borrower-
    # registration gap, which includes a cold worker spawn.
    old_ttl = cfg.transfer_pin_ttl_s
    cfg.set("transfer_pin_ttl_s", 1.5)
    rt = ray_tpu.init(num_cpus=2, object_store_memory=256 << 20)
    extra = rt.add_node(num_cpus=2, object_store_bytes=256 << 20)
    node_ids = [rt._nodes[0].node_id, extra.node_id]

    # Warm one worker per node: cold spawns must not eat into the pin
    # window during the tests themselves.
    @ray_tpu.remote
    def _warm():
        return 1

    futs = [_warm.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(node_id=nid)
    ).remote() for nid in node_ids]
    assert ray_tpu.get(futs, timeout=60) == [1, 1]
    yield rt, node_ids
    cfg.set("transfer_pin_ttl_s", old_ttl)
    ray_tpu.shutdown()


def test_borrowed_ref_through_nested_task_keeps_object_alive(cluster2):
    """driver put -> outer task (other node) -> nested inner task; the
    driver deletes its ref while inner still holds the borrow. The value
    must survive until inner reads it."""
    rt, node_ids = cluster2
    data = np.arange(1 << 20, dtype=np.uint8)
    expected = int(data.sum())
    ref = ray_tpu.put(data)

    @ray_tpu.remote
    def inner(refs):
        # Outlive the driver's del + the shortened transfer pin + a
        # refcount sweep, THEN read the borrowed object.
        time.sleep(3.0)
        return int(ray_tpu.get(refs[0]).sum())

    @ray_tpu.remote
    def outer(refs):
        # Re-borrow: pass the ref onward to a nested task on another
        # node and return that task's ref (the outer task — and the
        # driver's submitted-task pin with it — finishes long before
        # inner reads the object).
        return inner.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(
                node_id=refs[1])).remote([refs[0]])

    fut = outer.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(
            node_id=node_ids[1])).remote([ref, node_ids[0]])
    inner_ref = ray_tpu.get(fut, timeout=60)
    # Drop the driver's LOCAL ref: from here on only the borrow chain
    # (outer's worker -> inner's worker) keeps the object alive.
    del ref
    assert ray_tpu.get(inner_ref, timeout=60) == expected


def test_borrowed_ref_released_after_borrower_drops(cluster2):
    """Once every borrower is done and the owner drops its refs, the
    owner releases the object (no leak — the borrow protocol's other
    half)."""
    rt, node_ids = cluster2
    ref = ray_tpu.put(np.ones(1 << 20, dtype=np.uint8))
    oid = ref.id()

    @ray_tpu.remote
    def touch(refs):
        return int(ray_tpu.get(refs[0])[0])

    assert ray_tpu.get(touch.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(
            node_id=node_ids[1])).remote([ref]), timeout=60) == 1
    assert rt.refcount.is_in_scope(oid)
    del ref
    deadline = time.monotonic() + 30
    while rt.refcount.is_in_scope(oid) and time.monotonic() < deadline:
        time.sleep(0.2)
    assert not rt.refcount.is_in_scope(oid), \
        "object still pinned after owner and borrowers dropped it"
