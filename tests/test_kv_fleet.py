"""Fleet KV-cache economy: tiered prefix-page objects (PR 18).

Store tier (no jax): deterministic page object ids, the pack/unpack
codec's corruption rejection, and the LocalKVPageStore LRU cap.

Engine tier (store-free, tier-1): evict -> spill -> re-install must be
TOKEN-IDENTICAL to pure recompute on a fresh engine sharing only the
page store; corrupted payloads and chain mismatches are rejected
without hurting output or leaking slots; tier transitions balance
under RTPU_DEBUG_RES; fleet-off engines stay byte-identical to today.

Cluster tier (needs the native store lib): spilled pages ride the real
shm arena + sharded head directory, and survive a SIGKILL'd replica —
the churn win the whole tier exists for.
"""

import time

import numpy as np
import pytest

BLOCK = 8


def _engine(**kw):
    from ray_tpu.serve.llm import LLMEngine

    base = dict(max_batch=1, max_len=96, prompt_buckets=[8, 16, 32],
                decode_chunk=4, seed=0, prefix_block=BLOCK)
    base.update(kw)
    return LLMEngine(**base)


def _store(cap=64 << 20):
    from ray_tpu.serve.engine.kv_fleet import LocalKVPageStore

    return LocalKVPageStore(capacity_bytes=cap)


P1 = list(range(1, 33))      # 32 tokens = 4 complete blocks @ BLOCK=8
P2 = list(range(100, 132))   # disjoint: admitting it evicts P1's slot


def _wait_objects(store, n, timeout=30.0):
    """Spill packing/putting happens on the engine's spill worker —
    poll until the store holds >= n objects."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        if store.stats()["objects"] >= n:
            return
        time.sleep(0.01)
    raise AssertionError(
        f"store never reached {n} objects: {store.stats()}")


def _spill_from_fresh_engine(store, **kw):
    """Run P1 then P2 through a fleet engine with one slot: admitting
    P2 evicts P1's resident prefix, spilling its complete blocks into
    ``store``. Returns (engine, P1 reference tokens)."""
    eng = _engine(kv_fleet_min_prefix_blocks=0, kv_fleet_store=store,
                  **kw)
    ref = eng.generate(P1, max_new_tokens=8)
    eng.generate(P2, max_new_tokens=8)
    _wait_objects(store, 4)  # P1's 4 complete blocks (prompt side)
    return eng, ref


# ------------------------------------------------------------ store tier


def test_page_object_id_deterministic_and_namespaced():
    from ray_tpu.serve.engine.kv_fleet import page_object_id

    ns_a, ns_b = b"a" * 20, b"b" * 20
    oid = page_object_id(ns_a, 12345)
    assert oid.binary() == page_object_id(ns_a, 12345).binary()
    assert len(oid.binary()) == 28
    assert oid.binary() != page_object_id(ns_a, 12346).binary()
    # Same chain hash under a different model fingerprint must resolve
    # to a DIFFERENT object: cross-model KV reuse is unreachable.
    assert oid.binary() != page_object_id(ns_b, 12345).binary()
    assert page_object_id(ns_a, -7)  # negative Python hashes are fine


def test_fleet_namespace_tracks_model_identity():
    from ray_tpu.models import llama
    from ray_tpu.serve.engine.kv_fleet import fleet_namespace

    cfg = llama.tiny_config(max_seq_len=96)
    base = fleet_namespace(cfg, 8, None, 0)
    assert base == fleet_namespace(cfg, 8, None, 0)
    assert base != fleet_namespace(cfg, 16, None, 0)      # block size
    assert base != fleet_namespace(cfg, 8, "int8", 0)     # quantize
    assert base != fleet_namespace(cfg, 8, None, 1)       # param seed


def test_pack_unpack_roundtrip_and_corruption_rejected():
    import zlib

    from ray_tpu.serve.engine.kv_fleet import pack_page, unpack_page

    k = np.arange(2 * 4 * 8 * 16, dtype=np.float32).reshape(2, 4, 8, 16)
    v = k * 2.0
    crc = zlib.crc32(k.tobytes()) ^ zlib.crc32(v.tobytes())
    raw = pack_page(list(range(8)), [11, 22], k, v, crc)
    page = unpack_page(raw)
    assert page is not None
    assert page["tokens"] == list(range(8))
    assert page["chain"] == [11, 22]
    np.testing.assert_array_equal(page["k_page"], k)
    np.testing.assert_array_equal(page["v_page"], v)
    # Flip one payload byte: the CRC covers the page BYTES, so decode
    # fails closed (None == treat as a store miss).
    bad = bytearray(raw)
    bad[-9] ^= 0xFF
    assert unpack_page(bytes(bad)) is None
    assert unpack_page(b"junk") is None
    assert unpack_page(raw[:40]) is None


def test_local_store_lru_byte_cap():
    from ray_tpu.serve.engine.kv_fleet import (LocalKVPageStore,
                                               page_object_id)

    store = LocalKVPageStore(capacity_bytes=3000)
    ns = b"n" * 20
    oids = [page_object_id(ns, i) for i in range(4)]
    for oid in oids:
        assert store.put(oid, b"x" * 1000)
    assert not store.put(oids[-1], b"dup")  # dedupe: second put is a no-op
    st = store.stats()
    assert st["bytes"] <= 3000 and st["evictions"] >= 1
    assert not store.contains(oids[0])  # oldest evicted first
    assert store.contains(oids[-1])
    assert store.get(oids[-1]) == b"x" * 1000
    assert store.delete(oids[-1]) and not store.contains(oids[-1])


# ------------------------------------------------------------ engine tier


def test_evict_spill_reinstall_token_identity():
    """The tentpole: blocks evicted from engine A's HBM spill into the
    shared page tier; a FRESH engine B (cold HBM, same model) pulls
    them back through install_page + chain verify and produces
    token-identical greedy output to pure recompute."""
    store = _store()
    eng_a, ref = _spill_from_fresh_engine(store)
    try:
        assert eng_a.stats()["kv_fleet_spilled_blocks"] >= 4
        eng_b = _engine(kv_fleet_min_prefix_blocks=0,
                        kv_fleet_store=store)
        try:
            out = eng_b.generate(P1, max_new_tokens=8)
            assert out["token_ids"] == ref["token_ids"]
            st = eng_b.stats()
            assert st["kv_fleet_hits"] == 1
            # Reuse is clamped to len(prompt)-1 like the local cache:
            # 3 of the 4 spilled blocks install, the last token prefills.
            assert st["kv_fleet_pulled_blocks"] == 3
            assert st["kv_fleet_tokens_reused"] == 3 * BLOCK
            assert out["cached_prefix_len"] == 3 * BLOCK
            assert eng_b.kv.free_slots() == eng_b.max_batch
        finally:
            eng_b.close()
    finally:
        eng_a.close()


def test_corrupted_payload_rejected_recomputes():
    """Bit-rot in the tier store (CRC mismatch) must read as a miss:
    output stays token-identical via recompute and the admission's
    slot is unharmed."""
    store = _store()
    eng_a, ref = _spill_from_fresh_engine(store)
    eng_a.close()
    # Corrupt EVERY spilled payload in place.
    with store._lock:
        for key, raw in list(store._objs.items()):
            bad = bytearray(raw)
            bad[-9] ^= 0xFF
            store._objs[key] = bytes(bad)
    eng_b = _engine(kv_fleet_min_prefix_blocks=0, kv_fleet_store=store)
    try:
        out = eng_b.generate(P1, max_new_tokens=8)
        assert out["token_ids"] == ref["token_ids"]
        st = eng_b.stats()
        assert st["kv_fleet_hits"] == 0
        assert st["kv_fleet_rejects"] >= 1
        assert eng_b.kv.free_slots() == eng_b.max_batch
    finally:
        eng_b.close()


def test_chain_mismatch_rejected_recomputes():
    """A payload whose bytes are intact but whose chain prefix
    disagrees with the prompt's (hash collision / wrong-prefix object)
    is rejected by the chain-verify seam, not installed."""
    from ray_tpu.serve.engine.kv_fleet import (fleet_namespace,
                                               pack_page,
                                               page_object_id,
                                               unpack_page)
    from ray_tpu.serve.engine.kv_manager import chain_hashes

    store = _store()
    eng_a, ref = _spill_from_fresh_engine(store)
    ns = fleet_namespace(eng_a.cfg, BLOCK, None, 0)
    eng_a.close()
    want = chain_hashes(P1, BLOCK)
    oid = page_object_id(ns, want[0])
    page = unpack_page(store.get(oid))
    assert page is not None
    store.delete(oid)
    # Valid CRC, wrong chain: only the verify seam can catch this.
    store.put(oid, pack_page(page["tokens"], [123456789],
                             page["k_page"], page["v_page"],
                             page["crc"]))
    eng_b = _engine(kv_fleet_min_prefix_blocks=0, kv_fleet_store=store)
    try:
        out = eng_b.generate(P1, max_new_tokens=8)
        assert out["token_ids"] == ref["token_ids"]
        st = eng_b.stats()
        assert st["kv_fleet_hits"] == 0 and st["kv_fleet_rejects"] >= 1
        assert eng_b.kv.free_slots() == eng_b.max_batch
    finally:
        eng_b.close()


def test_min_prefix_blocks_gate_blocks_short_pulls():
    store = _store()
    eng_a, ref = _spill_from_fresh_engine(store)
    eng_a.close()
    # Only 3 blocks are pullable (len-1 clamp); a floor of 4 vetoes.
    eng_b = _engine(kv_fleet_min_prefix_blocks=4, kv_fleet_store=store)
    try:
        out = eng_b.generate(P1, max_new_tokens=8)
        assert out["token_ids"] == ref["token_ids"]
        assert eng_b.stats()["kv_fleet_hits"] == 0
    finally:
        eng_b.close()


def test_fleet_off_is_byte_identical_surface():
    """The default (-1) builds NOTHING new: no transfer programs on a
    colocated engine, no spill hook, no fleet snapshot/stats keys."""
    eng = _engine()
    try:
        assert eng._fleet is None
        assert eng.kv.spill_hook is None
        assert eng.loop.kv_page == 0
        assert "kv_fleet_hits" not in eng.stats()
        snap = eng.load_snapshot()
        assert "fleet_kv_blocks" not in snap
        assert "fleet_kv_hashes" not in snap
    finally:
        eng.close()


def test_fleet_snapshot_and_crossover_stat():
    store = _store()
    eng_a, _ref = _spill_from_fresh_engine(store)
    try:
        snap = eng_a.load_snapshot()
        assert snap["fleet_kv_blocks"] >= 4
        assert len(snap["fleet_kv_hashes"]) >= 4
        st = eng_a.stats()
        # Pull-side costs are measured at engine start; the crossover
        # key is always present on a fleet engine (None until the
        # recompute side has its first post-compile sample).
        assert "kv_pull_vs_recompute_crossover_blocks" in st
        assert st["kv_fleet_pull_ms_per_page"] > 0.0
        co = st["kv_pull_vs_recompute_crossover_blocks"]
        assert co is None or co == -1 or co >= 1
    finally:
        eng_a.close()


def test_fleet_tier_transitions_balance_under_res_debug(monkeypatch):
    """RTPU_DEBUG_RES: every kv_page_obj acquire (a block exported for
    spill, a payload pulled for install) is released by the time the
    engines close — an abandoned tier transition is a leak."""
    monkeypatch.setenv("RTPU_DEBUG_RES", "1")
    from ray_tpu.devtools import res_debug

    res_debug.reset()
    store = _store()
    eng_a, ref = _spill_from_fresh_engine(store)
    eng_b = _engine(kv_fleet_min_prefix_blocks=0, kv_fleet_store=store)
    out = eng_b.generate(P1, max_new_tokens=8)
    assert out["token_ids"] == ref["token_ids"]
    assert eng_b.stats()["kv_fleet_hits"] == 1
    eng_a.close()
    eng_b.close()
    assert not res_debug.violations(), res_debug.violations()
    assert res_debug.outstanding("kv_page_obj").get("kv_page_obj", 0) \
        == 0
    res_debug.reset()


def test_eviction_under_preemption_cross_replica_resume():
    """ROADMAP carry-forward: a PREEMPTED session's parked KV pages are
    evicted under memory pressure, spill into the shared fleet store,
    and the session resumes TOKEN-IDENTICALLY on a DIFFERENT replica
    that pulls them back — priority park/resume (PR 19) composed with
    the spill tier (PR 18). Replica A never resumes the victim; the
    continuation (prompt + confirmed tokens, remaining budget) runs on
    replica B against the store alone."""
    eng_ref = _engine()
    try:
        ref = eng_ref.generate(P1, max_new_tokens=24)["token_ids"]
    finally:
        eng_ref.close()

    store = _store()
    eng_a = _engine(kv_fleet_min_prefix_blocks=0, kv_fleet_store=store)
    # The victim stays parked on A (the replica it must leave): resume
    # is disabled, so only the cross-replica continuation can finish it.
    eng_a._resume_tick = lambda: None
    try:
        lo = eng_a._make_request(P1, 24, None, stream=True, priority=0)
        eng_a._queue.put(lo)
        # First streamed token: lo holds the slot with sunk decode work
        # — the continuation below must splice, not recompute from zero.
        kind, val = lo.stream_queue.get(timeout=120)
        assert kind not in ("done", "error"), (kind, val)
        hi = eng_a._make_request(list(range(200, 216)), 8, None,
                                 priority=5)
        eng_a._queue.put(hi)
        deadline = time.time() + 120
        while not eng_a._parked:
            assert time.time() < deadline, "lo never parked"
            time.sleep(0.001)
        hi.future.result(timeout=120)
        # Memory pressure on A: a disjoint admission storms the slot
        # pool, evicting the parked session's resident prefix rows —
        # their complete blocks spill into the shared store.
        eng_a.generate(P2, max_new_tokens=8)
        _wait_objects(store, 4)  # the victim's 4 complete prompt blocks
        assert eng_a._preempts >= 1
        assert eng_a._parked and eng_a._parked[0] is lo
        prefix = list(lo.prompt_ids) + list(lo.generated)
        remaining = lo.remaining()
        assert lo.generated and remaining > 0
    finally:
        eng_a.close()

    eng_b = _engine(kv_fleet_min_prefix_blocks=0, kv_fleet_store=store)
    try:
        out = eng_b.generate(prefix, max_new_tokens=remaining)
        st = eng_b.stats()
    finally:
        eng_b.close()
    # Token identity across park + evict + spill + cross-replica pull.
    assert list(lo.generated) + out["token_ids"] == ref
    # ...and the resume really rode the fleet tier, not pure recompute.
    assert st["kv_fleet_hits"] >= 1
    assert st["kv_fleet_pulled_blocks"] >= 1


def test_router_fleet_term_scores_spilled_residency():
    """Score identity at weight 0 (the default) and a fleet boost when
    the deployment opts in — on a __new__-built Router, the satellite's
    compat contract."""
    from ray_tpu.serve._private.router import Router
    from ray_tpu.serve.engine.kv_manager import chain_hashes

    prompt = list(range(48))
    chain = chain_hashes(prompt, BLOCK)
    cold = {"slots": 4, "waiting": 0, "prefix_block_size": BLOCK}
    warm = dict(cold, fleet_kv_hashes=frozenset(chain))

    r = Router.__new__(Router)
    r._inflight = {}
    s_cold, _ = r._score("a", cold, chain, len(prompt))
    s_warm, _ = r._score("b", warm, chain, len(prompt))
    assert s_cold == s_warm  # default weight 0: byte-identical scores

    r._weights = {"fleet": 1.0}
    s_cold, _ = r._score("a", cold, chain, len(prompt))
    s_warm, d = r._score("b", warm, chain, len(prompt))
    assert s_warm > s_cold
    assert d == 0  # fleet residency is NOT an HBM prefix match
    # An HBM-resident prefix must still outrank the same depth held
    # only in the fleet tier (a pull costs a store roundtrip).
    r._weights = {"prefix": 1.5, "fleet": 0.75}
    hbm = dict(cold, prefix_hashes=frozenset(chain))
    s_hbm, d_hbm = r._score("c", hbm, chain, len(prompt))
    assert s_hbm > s_warm and d_hbm == len(chain)


# ----------------------------------------------------------- cluster tier


def _cluster_or_skip():
    from ray_tpu.core import shm_store

    try:
        shm_store._load_lib()
    except OSError as e:
        pytest.skip(f"native store lib unavailable: {e}")


@pytest.fixture(scope="module")
def fleet_cluster():
    _cluster_or_skip()
    import ray_tpu
    import ray_tpu.serve as serve

    rt = ray_tpu.init(num_cpus=16)
    yield rt
    serve.shutdown()
    ray_tpu.shutdown()


def test_fleet_pages_survive_replica_sigkill(fleet_cluster):
    """Churn: a killed replica's HBM cache dies with it, but its
    SPILLED pages live in the node's shm arena — still pullable, so
    the fleet hit rate survives the restart (ISSUE 18 acceptance)."""
    import ray_tpu
    import ray_tpu.serve as serve
    from ray_tpu.models import llama
    from ray_tpu.serve.engine.kv_fleet import (ClusterKVPageStore,
                                               fleet_namespace,
                                               page_object_id,
                                               unpack_page)
    from ray_tpu.serve.engine.kv_manager import chain_hashes
    from ray_tpu.serve.llm import build_llm_deployment

    ek = dict(max_batch=1, max_len=96, prompt_buckets=[8, 16, 32],
              decode_chunk=4, seed=0, prefix_block=BLOCK,
              kv_fleet_min_prefix_blocks=0)
    h = serve.run(build_llm_deployment(name="kvfleet", num_replicas=2,
                                       engine_kwargs=ek))
    refs = {}
    for p in (P1, P2):
        refs[tuple(p)] = h.remote(
            {"prompt_ids": p, "max_new_tokens": 8}).result(timeout=180)
    # Force evictions on every replica that held P1: single-slot
    # engines evict on each new prompt, so one more round of P2/P1
    # guarantees spills on whichever replicas served them.
    for p in (P2, P1, P2):
        out = h.remote({"prompt_ids": p,
                        "max_new_tokens": 8}).result(timeout=180)
        assert out["token_ids"] == refs[tuple(p)]["token_ids"]

    ns = fleet_namespace(llama.tiny_config(max_seq_len=96), BLOCK,
                         None, 0)
    store = ClusterKVPageStore(fleet_cluster)
    want = chain_hashes(P1, BLOCK)

    def pullable():
        return all(
            unpack_page(store.get(page_object_id(ns, hh)) or b"")
            is not None for hh in want[:3])

    deadline = time.time() + 60
    while time.time() < deadline and not pullable():
        time.sleep(0.2)
    assert pullable(), "P1's spilled pages never landed in the store"

    from ray_tpu.serve._private.controller import CONTROLLER_NAME

    controller = ray_tpu.get_actor(CONTROLLER_NAME)
    _v, replicas = ray_tpu.get(
        controller.get_replica_set.remote("kvfleet"), timeout=30)
    assert len(replicas) == 2
    ray_tpu.kill(replicas[0])
    # The dead replica's pages must REMAIN pullable from the node store
    # (the whole point of the spill tier)...
    assert pullable()

    # ...and traffic keeps flowing token-identically through the
    # survivor/restart, which can itself pull instead of recomputing.
    # Requests racing the controller's death report may land on the
    # corpse — that window is the router's to close, not this tier's,
    # so transient ActorDiedError retries until the set converges.
    from ray_tpu.exceptions import ActorDiedError

    def gen(p, deadline):
        while True:
            try:
                return h.remote({"prompt_ids": p,
                                 "max_new_tokens": 8}).result(
                                     timeout=180)
            except ActorDiedError:
                if time.time() > deadline:
                    raise
                time.sleep(0.5)

    deadline = time.time() + 120
    for _trip in range(3):
        for p in (P1, P2):
            out = gen(p, deadline)
            assert out["token_ids"] == refs[tuple(p)]["token_ids"]
