"""Deterministic chaos harness: FaultPlan engine units (tier-1), RPC-layer
injection over a live server/client pair (tier-1), failure-domain
reconciliation over in-process HeadServer + NodeManagers (skip without a
loadable store lib), and the standing kill-head / kill-node / drop-ack
scenarios over real subprocess clusters (slow).

Parity model: the reference's rpc_chaos.h scripted failures + the
NodeKiller/WorkerKiller chaos actors (_private/test_utils.py) + the GCS
FT suite (test_gcs_fault_tolerance.py), generalized from the
test_dataplane.py chaos-retry idiom.

Every scenario runs under a FIXED plan + seed: re-running it replays the
identical fault sequence (acceptance: 3/3 consecutive green).
"""

from __future__ import annotations

import threading
import time
import uuid

import pytest

from ray_tpu.core.config import GLOBAL_CONFIG as cfg
from ray_tpu.devtools import chaos
from ray_tpu.devtools.chaos import ChaosPlanError, FaultPlan


# --------------------------------------------------------------------------
# plan engine (no cluster, no store — tier-1)
# --------------------------------------------------------------------------


def test_plan_parse_defaults_and_repr():
    plan = FaultPlan.parse(
        "drop_request:method=push_*:role=worker;"
        "delay:secs=0.5;kill:role=head:nth=2")
    assert len(plan.rules) == 3
    r0, r1, r2 = plan.rules
    assert (r0.action, r0.method, r0.role, r0.side) == (
        "drop_request", "push_*", "worker", "request")
    assert r1.secs == 0.5 and r1.count is None  # unlimited without nth
    assert r2.nth == 2 and r2.count == 1  # nth rules are one-shot
    assert "kill" in repr(r2) and "nth=2" in repr(r2)


def test_plan_parse_rejects_garbage():
    with pytest.raises(ChaosPlanError, match="unknown chaos action"):
        FaultPlan.parse("explode:method=x")
    with pytest.raises(ChaosPlanError, match="key=value"):
        FaultPlan.parse("delay:whoops")
    with pytest.raises(ChaosPlanError, match="unknown key"):
        FaultPlan.parse("delay:wibble=3")


def test_plan_parse_peer_value_with_colon():
    """The documented peer=<ip:port> form: a ':'-split piece with no
    '=' folds into the preceding value instead of failing the parse."""
    plan = FaultPlan.parse("sever:peer=127.0.0.1:9000:method=echo")
    r = plan.rules[0]
    assert r.peer == "127.0.0.1:9000" and r.method == "echo"
    assert r.decide("", "echo", "request", peer="127.0.0.1:9000")
    assert not plan.rules[0].decide("", "echo", "request",
                                    peer="127.0.0.1:9001")


def test_invalid_plan_disables_loudly_not_fatally(capsys):
    """A malformed RTPU_CHAOS_PLAN must not crash every RPC dispatch in
    the cluster: current_plan() reports it once and runs with chaos
    disabled (the scenario's fault assertions then point at the plan)."""
    try:
        cfg.set("chaos_plan", "explode:method=x")
        assert chaos.current_plan() is None
        assert "invalid plan" in capsys.readouterr().out
        assert chaos.current_plan() is None  # cached; no repeat spam
        assert "invalid plan" not in capsys.readouterr().out
    finally:
        cfg.set("chaos_plan", "")


def test_nth_after_count_semantics():
    plan = FaultPlan.parse("drop_request:method=m:nth=2")
    fires = [bool(plan.actions_for("", "m", "request")) for _ in range(5)]
    assert fires == [False, True, False, False, False]

    plan = FaultPlan.parse("drop_request:method=m:after=2:count=3")
    fires = [bool(plan.actions_for("", "m", "request")) for _ in range(7)]
    assert fires == [False, False, True, True, True, False, False]


def test_role_method_side_scoping():
    plan = FaultPlan.parse("drop_response:method=kill_actor:role=worker")
    assert not plan.actions_for("worker", "kill_actor", "request")
    assert not plan.actions_for("head", "kill_actor", "response")
    assert not plan.actions_for("worker", "heartbeat", "response")
    assert plan.actions_for("worker", "kill_actor", "response")


def test_prob_rules_are_seed_deterministic():
    a = FaultPlan.parse("drop_request:method=m:prob=0.3:seed=7")
    b = FaultPlan.parse("drop_request:method=m:prob=0.3:seed=7")
    seq_a = [bool(a.actions_for("", "m", "request")) for _ in range(200)]
    seq_b = [bool(b.actions_for("", "m", "request")) for _ in range(200)]
    assert seq_a == seq_b
    assert 20 < sum(seq_a) < 120  # actually probabilistic, not all/none
    c = FaultPlan.parse("drop_request:method=m:prob=0.3:seed=8")
    seq_c = [bool(c.actions_for("", "m", "request")) for _ in range(200)]
    assert seq_a != seq_c


def test_plan_cache_tracks_config_changes():
    try:
        cfg.set("chaos_plan", "delay:method=x:secs=0.1")
        p1 = chaos.current_plan()
        assert p1 is not None and p1.rules[0].secs == 0.1
        assert chaos.current_plan() is p1  # cached
        cfg.set("chaos_plan", "delay:method=x:secs=0.2")
        p2 = chaos.current_plan()
        assert p2 is not p1 and p2.rules[0].secs == 0.2
    finally:
        cfg.set("chaos_plan", "")
    assert chaos.current_plan() is None
    assert not chaos.chaos_enabled()


def test_plan_rearm_after_clear_resets_counters():
    """chaos_plan='' then the SAME plan string again must re-parse with
    fresh counters — a spent nth-rule from the previous arming must not
    silently disable the re-armed plan."""
    plan_str = "drop_request:method=m:nth=1"
    try:
        cfg.set("chaos_plan", plan_str)
        assert chaos.current_plan().actions_for("", "m", "request")
        cfg.set("chaos_plan", "")
        assert chaos.current_plan() is None
        cfg.set("chaos_plan", plan_str)
        assert chaos.current_plan().actions_for("", "m", "request"), \
            "re-armed plan inherited spent counters"
    finally:
        cfg.set("chaos_plan", "")


# --------------------------------------------------------------------------
# protocol integration (real sockets, no cluster — tier-1)
# --------------------------------------------------------------------------


class _EchoHandler:
    chaos_role = "node"
    # Local classification (RTPU_DEBUG_RPC witness + dist lint): echo is
    # a pure function, safe to retry/re-deliver.
    extra_retry_safe_rpcs = frozenset({"echo"})

    def __init__(self):
        self.calls = 0

    def rpc_echo(self, conn, x):
        self.calls += 1
        return x

    def rpc_ping(self, conn):  # name IS in RETRY_SAFE_RPCS
        return "pong"


@pytest.fixture
def rpc_pair():
    from ray_tpu.cluster.protocol import RpcClient, RpcServer

    h = _EchoHandler()
    server = RpcServer(h).start()
    client = RpcClient(server.address)
    yield h, server, client
    cfg.set("chaos_plan", "")
    cfg.set("rpc_chaos_failure_prob", 0.0)
    client.close()
    server.stop()


def test_drop_request_then_retry_recovers(rpc_pair):
    h, _s, client = rpc_pair
    cfg.set("chaos_plan", "drop_request:role=node:method=echo:nth=1")
    with pytest.raises(TimeoutError):
        client.call("echo", 1, timeout=0.5)
    assert h.calls == 0  # the handler never saw the dropped request
    assert client.call("echo", 2, timeout=10) == 2  # one-shot rule spent


def test_drop_response_runs_handler_but_loses_reply(rpc_pair):
    h, _s, client = rpc_pair
    cfg.set("chaos_plan", "drop_response:method=echo:nth=1")
    with pytest.raises(TimeoutError):
        client.call("echo", 1, timeout=0.5)
    assert h.calls == 1  # side effect happened; only the ack was lost
    assert client.call("echo", 2, timeout=10) == 2


def test_delay_rule_adds_latency(rpc_pair):
    _h, _s, client = rpc_pair
    cfg.set("chaos_plan", "delay:method=echo:secs=0.4:count=1")
    t0 = time.monotonic()
    assert client.call("echo", 3, timeout=10) == 3
    assert time.monotonic() - t0 >= 0.35
    t0 = time.monotonic()
    assert client.call("echo", 4, timeout=10) == 4  # count spent
    assert time.monotonic() - t0 < 0.3


def test_sever_kills_connection_and_retrying_call_recovers(rpc_pair):
    from ray_tpu.cluster.protocol import ConnectionLost

    _h, _s, client = rpc_pair
    cfg.set("chaos_plan", "sever:method=echo:nth=1")
    with pytest.raises((ConnectionLost, TimeoutError)):
        client.call("echo", 1, timeout=5)
    client.reconnect()
    assert client.call("echo", 2, timeout=10) == 2
    # retrying_call rides a sever transparently (reconnect + retry).
    cfg.set("chaos_plan", "sever:method=echo:nth=1")
    assert client.retrying_call("echo", 3, timeout=5) == 3


def test_kill_action_reaches_kill_hook(rpc_pair, monkeypatch):
    _h, _s, client = rpc_pair
    hits = []
    monkeypatch.setattr(chaos, "_kill_self", lambda: hits.append(1))
    cfg.set("chaos_plan", "kill:role=node:method=echo:nth=1")
    with pytest.raises(TimeoutError):
        # Under the monkeypatch the frame is dropped instead of the
        # process dying; the real SIGKILL path is covered by the slow
        # scenarios below.
        client.call("echo", 1, timeout=0.5)
    assert hits == [1]


def test_blind_chaos_only_drops_retry_safe_methods(rpc_pair):
    from ray_tpu.cluster.protocol import RETRY_SAFE_RPCS

    h, _s, client = rpc_pair
    assert "ping" in RETRY_SAFE_RPCS and "echo" not in RETRY_SAFE_RPCS
    cfg.set("rpc_chaos_failure_prob", 1.0)
    # Non-retry-safe method: NEVER blindly dropped, first try lands.
    assert client.call("echo", 7, timeout=10) == 7
    # Retry-safe method: dropped at p=1.
    with pytest.raises(TimeoutError):
        client.call("ping", timeout=0.5)
    cfg.set("rpc_chaos_failure_prob", 0.0)
    assert client.call("ping", timeout=10) == "pong"


def test_retrying_call_outlasts_respawn_window(rpc_pair):
    """A peer that is DOWN for ~2x the backoff-exhaustion time but comes
    back within rpc_retry_min_window_s is ridden out — the pre-fix
    attempt counting gave up in ~3s, less than a head/node respawn."""
    from ray_tpu.cluster.protocol import RpcClient, RpcServer

    h, server, client = rpc_pair
    host, port = server.address.rsplit(":", 1)
    server.stop()  # peer "dies"; the port is gone
    restarted = {}

    def respawn():
        time.sleep(4.0)  # longer than 5 attempts' ~3.1s of backoff
        s2 = RpcServer(h, host=host, port=int(port)).start()
        restarted["server"] = s2

    threading.Thread(target=respawn, daemon=True).start()
    try:
        assert client.retrying_call("echo", 42, timeout=5) == 42
    finally:
        s2 = restarted.get("server")
        if s2 is not None:
            s2.stop()


# --------------------------------------------------------------------------
# failure-domain reconciliation (in-process head + node manager; needs a
# loadable native store lib — skips where the checked-in .so cannot load)
# --------------------------------------------------------------------------


def _node_or_skip(head_addr: str, resources=None):
    from ray_tpu.core import shm_store

    try:
        shm_store._load_lib()
    except OSError as e:
        pytest.skip(f"native store lib unavailable: {e}")
    from ray_tpu.cluster.node_manager import NodeManager

    return NodeManager(head_addr, uuid.uuid4().hex,
                       resources or {"CPU": 2.0}, {}, 64 << 20)


class _FakeProc:
    def poll(self):
        return None

    def terminate(self):
        pass

    def kill(self):
        pass

    def wait(self, timeout=None):
        return 0


def test_head_restart_rehydrates_directory_and_reconciles_leases():
    """The two head-restart invariants, driven synchronously:

    1. holder-set rehydration — a head that restarts with an empty
       object directory relearns this node's copies from the node's
       local mirror on re-registration;
    2. era reconciliation — a lease granted to the DEAD head's in-flight
       actor creation (lessee "head:<old-era>") is returned, while an
       actor-hosting lease survives."""
    from ray_tpu.core.ids import ObjectID
    from ray_tpu.cluster.head import HeadServer
    from ray_tpu.cluster.node_manager import Lease, WorkerProc

    head = HeadServer()
    nm = _node_or_skip(head.address)
    try:
        old_inc = head.incarnation
        assert nm._head_incarnation == old_inc
        # An owner-published object (the batch routes through the node).
        oid = ObjectID.from_random()
        mv = nm.store.create_buffer(oid, 1024)
        mv[:] = b"x" * 1024
        nm.store.seal(oid)
        nm.rpc_object_batch(None, [("add", oid.binary(), 1024)])
        _wait_until(lambda: head.rpc_object_locations(
            None, oid.binary()), 10, "object never reached the head")

        # Two head-era leases: one mid-creation (no actor), one landed.
        def fake_lease(lid, actor_host):
            w = WorkerProc(_FakeProc(), uuid.uuid4().hex)
            w.ready.set()
            w.address = f"fake:{lid}"
            w.is_actor_host = actor_host
            lease = Lease(lid, w, {"CPU": 1.0}, "main",
                          lessee=f"head:{old_inc}")
            with nm._lock:
                nm._workers[w.worker_id] = w
                nm._leases[lid] = lease
                nm.available["CPU"] -= 1.0
            return lease

        fake_lease("stale-era", actor_host=False)
        fake_lease("actor-host", actor_host=True)

        # Head "restarts": fresh process state on the same port.
        port = int(head.address.rsplit(":", 1)[1])
        head.shutdown()
        head2 = HeadServer(port=port)
        try:
            assert head2.incarnation != old_inc
            assert head2.rpc_object_locations(None, oid.binary()) == []
            # The node's next heartbeat gets False -> re-register ->
            # republish + reconcile.
            _wait_until(lambda: head2.rpc_object_locations(
                None, oid.binary()), 20,
                "holder set never republished after head restart")
            _wait_until(lambda: "stale-era" not in nm._leases, 10,
                        "stale head-era lease never reconciled")
            with nm._lock:
                assert "actor-host" in nm._leases  # landed actor stays
                assert nm.available["CPU"] == 1.0  # stale lease refunded
        finally:
            head2.shutdown()
            head = None  # already shut down
    finally:
        nm.shutdown()
        if head is not None:
            head.shutdown()


def test_pull_survives_severed_holder_connection():
    """Mid-pull connection loss to the holder (sever on fetch_object
    chunk 2) must not wedge or corrupt the pull: the retry lap
    re-fetches and the object arrives intact (the test_dataplane
    chaos-retry idiom generalized to the pull manager)."""
    import os as _os

    from ray_tpu.core.ids import ObjectID
    from ray_tpu.cluster.head import HeadServer

    head = HeadServer()
    holder = _node_or_skip(head.address)
    puller = _node_or_skip(head.address)
    old_chunk = cfg.object_transfer_chunk_bytes
    try:
        oid = ObjectID.from_random()
        data = _os.urandom(3 << 20)
        mv = holder.store.create_buffer(oid, len(data))
        mv[:] = data
        holder.store.seal(oid)
        head.rpc_object_added(None, oid.binary(), holder.node_id,
                              len(data))
        cfg.set("object_transfer_chunk_bytes", 1 << 20)  # 3 chunks
        cfg.set("chaos_plan", "sever:role=node:method=fetch_object:nth=2")
        assert puller.rpc_pull_object(None, oid.binary(), 30000) is True
        buf = puller.store.get(oid, timeout_ms=1000)
        assert bytes(buf.buffer) == data
        buf.release()
    finally:
        cfg.set("chaos_plan", "")
        cfg.set("object_transfer_chunk_bytes", old_chunk)
        puller.shutdown()
        holder.shutdown()
        head.shutdown()


def _wait_until(fn, timeout_s, msg):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if fn():
            return
        time.sleep(0.1)
    raise AssertionError(msg)


# --------------------------------------------------------------------------
# standing scenarios (subprocess clusters, SIGKILL faults — slow)
# --------------------------------------------------------------------------


@pytest.fixture
def chaos_cluster(request):
    """A real subprocess cluster booted under a FIXED chaos plan (the
    plan + seed ride RTPU_CHAOS_PLAN env into every spawned process)."""
    import ray_tpu

    plan = request.param

    def boot(num_cpus=2):
        rt = ray_tpu.init(num_cpus=num_cpus,
                          _system_config={"chaos_plan": plan,
                                          "chaos_seed": 42})
        return rt

    yield boot
    import ray_tpu

    ray_tpu.shutdown()
    cfg.set("chaos_plan", "")


@pytest.mark.slow
@pytest.mark.parametrize(
    "chaos_cluster", ["kill:role=head:method=register_actor:nth=2"],
    indirect=True)
def test_scenario_kill_head_mid_submission(chaos_cluster):
    """The head SIGKILLs itself as the 2nd actor registration arrives.
    The supervisor respawns it on the same port with its durable tables;
    the submitter's retrying_call rides the outage; the node republishes
    its holder sets so a pre-kill object stays pullable; no lease leaks."""
    import numpy as np

    import ray_tpu as rt
    from ray_tpu.core.runtime_context import require_runtime
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy)

    runtime = chaos_cluster()
    node_b = runtime.add_node(num_cpus=2)
    time.sleep(1.5)

    @rt.remote(scheduling_strategy=NodeAffinitySchedulingStrategy(
        node_id=node_b.node_id, soft=True))
    def produce():
        return np.arange(300_000)

    ref = produce.remote()
    ready, _ = rt.wait([ref], num_returns=1, timeout=90, fetch_local=False)
    assert ready

    @rt.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def inc(self):
            self.n += 1
            return self.n

    old_pid = runtime._head_proc.pid
    c1 = Counter.remote()  # registration 1: survives
    assert rt.get(c1.inc.remote(), timeout=60) == 1
    c2 = Counter.remote()  # registration 2: SIGKILLs the head
    assert rt.get(c2.inc.remote(), timeout=120) == 1
    assert runtime._head_proc.pid != old_pid, "head did not respawn"

    # Fresh work flows, and the restarted head's directory was
    # REHYDRATED: it lists a holder for the pre-kill object (pull rides
    # the directory, not lineage re-execution).
    @rt.remote
    def ping(i):
        return i

    assert rt.get([ping.remote(i) for i in range(8)],
                  timeout=120) == list(range(8))
    _wait_until(
        lambda: runtime.head.retrying_call(
            "object_locations", ref.id().binary(), timeout=10),
        30, "holder set never republished to the restarted head")
    got = rt.get(ref, timeout=90)
    assert got[0] == 0 and got[-1] == 299_999
    _assert_leases_drain(runtime, allowed_actor_hosts=2)


@pytest.mark.slow
@pytest.mark.parametrize(
    "chaos_cluster", ["kill:role=node:method=fetch_object:nth=2"],
    indirect=True)
def test_scenario_kill_holder_mid_chunked_pull(chaos_cluster):
    """The holder node SIGKILLs itself serving chunk 2 of a chunked
    pull. The puller's in-flight sink must not be corrupted; the get()
    completes via lineage re-execution once the head scrubs the dead
    holder from the directory."""
    import numpy as np

    import ray_tpu as rt
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy)

    runtime = chaos_cluster()
    node_b = runtime.add_node(num_cpus=2)
    time.sleep(1.5)
    n = 3_000_000  # ~24 MB -> 6 chunks at the default 4 MB

    @rt.remote(scheduling_strategy=NodeAffinitySchedulingStrategy(
        node_id=node_b.node_id, soft=True))
    def produce():
        return np.arange(n)

    ref = produce.remote()
    ready, _ = rt.wait([ref], num_returns=1, timeout=90, fetch_local=False)
    assert ready
    got = rt.get(ref, timeout=120)  # chunk 2 kills the holder mid-pull
    assert got[0] == 0 and got[-1] == n - 1
    assert node_b.proc.poll() is not None, "holder should be dead"
    _assert_leases_drain(runtime, allowed_actor_hosts=0)


@pytest.mark.slow
@pytest.mark.parametrize(
    "chaos_cluster",
    ["drop_response:role=worker:method=kill_actor:count=2"],
    indirect=True)
def test_scenario_dropped_actor_kill_acks(chaos_cluster):
    """The first two kill_actor acks are lost: the head's re-ack loop
    must still land the kill — no zombie actor keeps answering, and the
    actor's worker lease is reclaimed (head.py's 'a chaos-dropped kill
    would leave a zombie actor' comment, now exercised)."""
    import ray_tpu as rt
    from ray_tpu.exceptions import ActorDiedError

    runtime = chaos_cluster()

    @rt.remote
    class Svc:
        def ping(self):
            return "pong"

    a = Svc.remote()
    assert rt.get(a.ping.remote(), timeout=60) == "pong"
    rt.kill(a)
    with pytest.raises(ActorDiedError):
        rt.get(a.ping.remote(), timeout=30)
    _assert_leases_drain(runtime, allowed_actor_hosts=0)


@pytest.mark.slow
@pytest.mark.parametrize(
    "chaos_cluster", ["kill:role=head:method=create_pg:nth=2"],
    indirect=True)
def test_scenario_head_restart_with_inflight_pg_and_queued_leases(
        chaos_cluster):
    """The head dies receiving the 2nd create_pg (in-flight bundle
    reservation) while plain tasks are queued. The respawned head must
    complete the reservation on the client's retry, the queued leases
    must flow, and PG-placed work must run."""
    import ray_tpu as rt
    from ray_tpu.util.placement_group import (placement_group,
                                              remove_placement_group)
    from ray_tpu.util.scheduling_strategies import (
        PlacementGroupSchedulingStrategy)

    runtime = chaos_cluster(num_cpus=4)
    pg1 = placement_group([{"CPU": 1}], strategy="PACK")
    assert pg1.ready(timeout=60)
    old_pid = runtime._head_proc.pid

    @rt.remote
    def ping(i):
        return i

    refs = [ping.remote(i) for i in range(4)]  # queued across the outage
    pg2 = placement_group([{"CPU": 1}, {"CPU": 1}],
                          strategy="PACK")  # kills the head
    assert pg2.ready(timeout=90)
    assert runtime._head_proc.pid != old_pid, "head did not respawn"

    @rt.remote(scheduling_strategy=PlacementGroupSchedulingStrategy(
        placement_group=pg2))
    def inside():
        return "in-pg"

    assert rt.get(inside.remote(), timeout=60) == "in-pg"
    assert rt.get(refs, timeout=120) == list(range(4))
    remove_placement_group(pg2)
    remove_placement_group(pg1)
    _assert_leases_drain(runtime, allowed_actor_hosts=0)


@pytest.fixture
def plain_cluster():
    """Subprocess cluster with NO chaos plan: scenarios drive real
    SIGKILLs from the test body (the all-holders-dead shapes kill two
    processes at once, which the one-process-kills-itself plan grammar
    cannot express)."""
    import ray_tpu

    def boot(num_cpus=2):
        return ray_tpu.init(num_cpus=num_cpus)

    yield boot
    import ray_tpu

    ray_tpu.shutdown()


@pytest.mark.slow
def test_scenario_all_holders_dead_actor(plain_cluster):
    """A registered actor's host NODE and the head die TOGETHER. No
    worker_dead_at report can ever arrive (its target died too), and
    the respawned head recovers the actor ALIVE from sqlite pointing at
    a node that will never re-register. The recovered-ALIVE watch must
    declare it dead after the grace window and re-drive it through
    max_restarts; the caller's queued calls replay onto the new
    incarnation (at-least-once) — PR 8's harness could not pass this
    because the head had no durable actor table and no zombie-ALIVE
    sweep."""
    import os
    import signal

    import ray_tpu as rt
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy)

    runtime = plain_cluster()
    node_b = runtime.add_node(num_cpus=2)
    time.sleep(1.5)

    @rt.remote(max_restarts=2, max_task_retries=-1,
               scheduling_strategy=NodeAffinitySchedulingStrategy(
                   node_id=node_b.node_id, soft=True))
    class Svc:
        def __init__(self):
            self.n = 0

        def inc(self):
            self.n += 1
            return self.n

    a = Svc.remote()
    assert rt.get(a.inc.remote(), timeout=60) == 1
    info = runtime.head.retrying_call("get_actor_info",
                                      a._actor_id.binary(), timeout=15)
    assert info["state"] == "ALIVE"  # placed on node_b (soft affinity)
    head_pid = runtime._head_proc.pid
    # Kill BOTH: the actor's host node first (so its death report has no
    # live head to land on), then the head before its health sweep can
    # notice the node.
    node_b.proc.kill()
    os.kill(head_pid, signal.SIGKILL)
    # Queued during the outage: must park (restart-pending queueing),
    # then replay against the re-created incarnation on node A.
    refs = [a.inc.remote() for _ in range(4)]
    vals = rt.get(refs, timeout=180)
    # Fresh incarnation: counter restarts from 0; exactly-once per
    # incarnation means the four replayed calls count 1..4.
    assert vals == [1, 2, 3, 4], vals
    assert runtime._head_proc.pid != head_pid, "head did not respawn"
    info = runtime.head.retrying_call("get_actor_info",
                                      a._actor_id.binary(), timeout=15)
    assert info["state"] == "ALIVE"
    assert info["restarts"] >= 1
    _assert_leases_drain(runtime, allowed_actor_hosts=1)


@pytest.mark.slow
def test_scenario_all_holders_dead_object_while_head_down(plain_cluster):
    """Every holder of an object dies WHILE the head is down. The
    respawned head's directory rehydrates only from surviving nodes —
    none has a copy — so the owner's get() must fall through to lineage
    re-execution (sqlite brings the control plane back; lineage brings
    the data back)."""
    import os
    import signal

    import numpy as np

    import ray_tpu as rt
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy)

    runtime = plain_cluster()
    node_b = runtime.add_node(num_cpus=2)
    time.sleep(1.5)
    n = 500_000

    @rt.remote(scheduling_strategy=NodeAffinitySchedulingStrategy(
        node_id=node_b.node_id, soft=True))
    def produce():
        return np.arange(n)

    ref = produce.remote()
    ready, _ = rt.wait([ref], num_returns=1, timeout=90, fetch_local=False)
    assert ready
    head_pid = runtime._head_proc.pid
    os.kill(head_pid, signal.SIGKILL)  # head down first...
    node_b.proc.kill()                 # ...then the only holder dies
    got = rt.get(ref, timeout=180)     # recovers via lineage post-respawn
    assert got[0] == 0 and got[-1] == n - 1
    assert runtime._head_proc.pid != head_pid, "head did not respawn"
    _assert_leases_drain(runtime, allowed_actor_hosts=0)


@pytest.mark.slow
def test_scenario_node_death_recreates_actor_and_replays_calls(
        plain_cluster):
    """The one-continuous-story scenario: host node dies (head alive),
    head's health sweep restarts the actor on another node via
    max_restarts, and the caller's unacked calls replay there."""
    import ray_tpu as rt
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy)

    runtime = plain_cluster()
    node_b = runtime.add_node(num_cpus=2)
    time.sleep(1.5)

    @rt.remote(max_restarts=1, max_task_retries=-1,
               scheduling_strategy=NodeAffinitySchedulingStrategy(
                   node_id=node_b.node_id, soft=True))
    class Svc:
        def __init__(self):
            self.n = 0

        def inc(self):
            self.n += 1
            return self.n

    a = Svc.remote()
    assert rt.get(a.inc.remote(), timeout=60) == 1
    refs = [a.inc.remote() for _ in range(6)]
    runtime.kill_node(node_b)
    vals = rt.get(refs, timeout=180)
    # Some of the 6 may have executed on the dying incarnation with
    # results delivered (those keep their old-incarnation values); the
    # rest replay in order onto the fresh one. NONE may fail, and every
    # replayed run must be exactly-once (strictly increasing counter
    # runs — a duplicate execution would repeat or skip a value).
    assert len(vals) == 6
    assert all(isinstance(v, int) for v in vals), vals
    runs = [vals[i] for i in range(len(vals))
            if i == 0 or vals[i] != vals[i - 1] + 1]
    assert len(runs) <= 2, f"more than one incarnation boundary: {vals}"
    # The restarted incarnation answers fresh calls.
    assert rt.get(a.inc.remote(), timeout=60) >= 1
    _wait_until(
        lambda: runtime.head.retrying_call(
            "get_actor_info", a._actor_id.binary(),
            timeout=15)["restarts"] >= 1,
        60, "actor never restarted after node death")
    _assert_leases_drain(runtime, allowed_actor_hosts=1)


@pytest.mark.slow
def test_scenario_rolling_head_upgrade_zero_failures(plain_cluster):
    """The rolling-upgrade scenario (devtools.chaos.run_rolling_upgrade):
    drain -> sqlite checkpoint -> old head releases the port -> new
    incarnation serves, under continuous task + actor-call load.
    Acceptance: ZERO failed client requests — latency may spike while
    requests ride their retry loops across the gap, failures fail."""
    import ray_tpu as rt

    runtime = plain_cluster()

    @rt.remote
    def ping(i):
        return i

    @rt.remote(max_restarts=1, max_task_retries=-1)
    class Echo:
        def hit(self, i):
            return i

    e = Echo.remote()
    assert rt.get(e.hit.remote(-1), timeout=60) == -1

    def request(i):
        if i % 2:
            assert rt.get(ping.remote(i), timeout=120) == i
        else:
            assert rt.get(e.hit.remote(i), timeout=120) == i

    report = chaos.run_rolling_upgrade(runtime, request, clients=2)
    assert report["request_failures"] == [], report["request_failures"]
    assert report["requests_ok"] > 0
    assert report["new_incarnation"] != report["old_incarnation"]
    # The upgraded head serves fresh work and the actor survived.
    assert rt.get(e.hit.remote(99), timeout=60) == 99
    assert rt.get([ping.remote(i) for i in range(4)],
                  timeout=90) == list(range(4))
    _assert_leases_drain(runtime, allowed_actor_hosts=1)


def _assert_leases_drain(runtime, allowed_actor_hosts: int,
                         timeout_s: float = 45.0) -> None:
    """Post-scenario invariant: once the workload drains, every
    non-actor lease is returned (nothing leaked through the faults)."""
    deadline = time.monotonic() + timeout_s
    last = None
    while time.monotonic() < deadline:
        try:
            census = runtime.head.retrying_call("cluster_leases",
                                                timeout=15)
        except Exception:
            time.sleep(0.5)
            continue
        entries = [v for v in census.values() if isinstance(v, dict)]
        # An unreachable node's census entry is MISSING data, not zero
        # leases: the pass requires every alive node to have answered.
        errors = [v["error"] for v in entries if "error" in v]
        leases = [l for v in entries for l in v.get("leases", ())]
        last = (leases, errors)
        non_actor = [l for l in leases if not l.get("is_actor_host")]
        hosts = [l for l in leases if l.get("is_actor_host")]
        if not errors and not non_actor \
                and len(hosts) <= allowed_actor_hosts:
            return
        time.sleep(0.5)
    raise AssertionError(f"leases leaked after drain: {last}")
