"""DQN + replay buffers + LearnerGroup + actor collectives (reference
test model: rllib DQN tuned_examples learning gates,
util/collective tests, learner_group multi-learner tests)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib import DQNConfig, DQNLearner, LearnerGroup
from ray_tpu.rllib.replay_buffers import (PrioritizedReplayBuffer,
                                          ReplayBuffer)


@pytest.fixture(scope="module")
def cluster():
    rt = ray_tpu.init(num_cpus=4)
    yield rt
    ray_tpu.shutdown()


# ------------------------------------------------------------ replay buffer

def test_replay_buffer_ring_and_sample():
    buf = ReplayBuffer(100, obs_size=3, seed=0)
    for start in range(0, 260, 20):
        n = 20
        buf.add_batch(np.full((n, 3), start, np.float32),
                      np.arange(n, dtype=np.int32) % 2,
                      np.ones(n, np.float32),
                      np.full((n, 3), start + 1, np.float32),
                      np.zeros(n, np.float32))
    assert len(buf) == 100  # ring capped
    s = buf.sample(32)
    assert s["obs"].shape == (32, 3)
    # Ring overwrote the oldest: only the last 100 rows' markers remain.
    assert s["obs"].min() >= 160


def test_prioritized_buffer_biases_sampling():
    buf = PrioritizedReplayBuffer(64, obs_size=1, alpha=1.0, seed=0)
    buf.add_batch(np.zeros((64, 1), np.float32),
                  np.zeros(64, np.int32), np.zeros(64, np.float32),
                  np.zeros((64, 1), np.float32), np.zeros(64, np.float32))
    # Give index 7 a huge priority; it must dominate samples.
    buf.update_priorities(np.arange(64), np.full(64, 1e-3))
    buf.update_priorities(np.array([7]), np.array([100.0]))
    s = buf.sample(512)
    frac = float((s["indices"] == 7).mean())
    assert frac > 0.5, frac
    assert s["weights"].shape == (512,)


# ----------------------------------------------------------------- learner

def test_dqn_learner_reduces_td_error():
    rng = np.random.default_rng(0)
    learner = DQNLearner(4, 2, lr=5e-3, target_update_freq=10, seed=0)
    batch = {
        "obs": rng.normal(size=(256, 4)).astype(np.float32),
        "actions": rng.integers(0, 2, 256).astype(np.int32),
        "rewards": rng.normal(size=256).astype(np.float32),
        "next_obs": rng.normal(size=(256, 4)).astype(np.float32),
        "dones": (rng.random(256) < 0.1).astype(np.float32),
    }
    first = learner.update_from_batch(batch)["loss"]
    for _ in range(50):
        last = learner.update_from_batch(batch)["loss"]
    assert last < first, (first, last)


def test_dqn_cartpole_learning_gate():
    """Second learning-regression gate in the suite (VERDICT item 7):
    CartPole mean return >= 130 within a bounded budget."""
    algo = (DQNConfig()
            .environment("CartPole")
            .env_runners(num_env_runners=0, num_envs_per_env_runner=8,
                         rollout_fragment_length=32)
            .training(lr=1e-3, train_batch_size=64,
                      target_network_update_freq=250,
                      num_steps_sampled_before_learning_starts=1000,
                      updates_per_iteration=32)
            .build())
    best = 0.0
    try:
        for _ in range(120):
            result = algo.train()
            ret = result["env_runners"]["episode_return_mean"]
            if ret is not None:
                best = max(best, ret)
            if best >= 130.0:
                break
    finally:
        algo.stop()
    assert best >= 130.0, f"DQN failed to reach 130 on CartPole ({best})"


# ------------------------------------------------------------- collectives

def test_collective_allreduce_allgather_8_actors(cluster):
    from ray_tpu.util import collective as col

    @ray_tpu.remote(num_cpus=0)
    class Rank:
        def __init__(self, rank, world):
            col.init_collective_group(world, rank, "test-gang")
            self.rank = rank
            self.world = world

        def run(self):
            out = col.allreduce(np.full(4, self.rank + 1.0), "test-gang")
            gathered = col.allgather(np.array([self.rank]), "test-gang")
            col.barrier("test-gang")
            chunk = col.reducescatter(np.arange(8.0), "test-gang")
            b = col.broadcast(
                np.array([42.0]) if self.rank == 3 else None,
                root=3, group_name="test-gang")
            return (out.tolist(), [g.tolist() for g in gathered],
                    chunk.tolist(), b.tolist())

    world = 8
    ranks = [Rank.remote(i, world) for i in range(world)]
    results = ray_tpu.get([r.run.remote() for r in ranks], timeout=120)
    expected_sum = float(sum(range(1, world + 1)))
    for rank, (red, gathered, chunk, b) in enumerate(results):
        assert red == [expected_sum] * 4
        assert gathered == [[i] for i in range(world)]
        assert chunk == [float(rank) * world]  # sum of 8 copies, split
        assert b == [42.0]
    for r in ranks:
        ray_tpu.kill(r)
    # The named coordinator must not outlive the gang in the shared
    # module cluster (a stale world_size poisons later groups).
    ray_tpu.kill(ray_tpu.get_actor("rtpu-collective-test-gang"))


def test_learner_group_multi_learner_matches_single(cluster):
    """2-learner DDP update == single-learner update on the same batch
    (mean gradient over shards == full-batch gradient when shards are
    equal halves)."""
    rng = np.random.default_rng(1)
    batch = {
        "obs": rng.normal(size=(128, 4)).astype(np.float32),
        "actions": rng.integers(0, 2, 128).astype(np.int32),
        "rewards": rng.normal(size=128).astype(np.float32),
        "next_obs": rng.normal(size=(128, 4)).astype(np.float32),
        "dones": np.zeros(128, np.float32),
    }

    def factory():
        return DQNLearner(4, 2, lr=1e-3, target_update_freq=1000, seed=7)

    single = LearnerGroup(factory, num_learners=0)
    multi = LearnerGroup(factory, num_learners=2,
                         group_name="lg-test")
    try:
        s1 = single.update_from_batch(dict(batch))
        s2 = multi.update_from_batch(dict(batch))
        assert "loss" in s1 and "loss" in s2
        w1 = single.get_weights()
        w2 = multi.get_weights()
        import jax

        for a, b in zip(jax.tree_util.tree_leaves(w1),
                        jax.tree_util.tree_leaves(w2)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)
        assert len(s2["td_errors"]) == 128
    finally:
        multi.stop()


def test_dqn_multi_learner_trains(cluster):
    """DQN through the 2-learner group still learns (short smoke: loss
    decreases and returns improve over the random baseline)."""
    algo = (DQNConfig()
            .environment("CartPole")
            .env_runners(num_env_runners=2, num_envs_per_env_runner=4,
                         rollout_fragment_length=32)
            .training(lr=1e-3, train_batch_size=64,
                      num_steps_sampled_before_learning_starts=500,
                      updates_per_iteration=32)
            .learners(num_learners=2)
            .build())
    best = 0.0
    try:
        for _ in range(45):
            result = algo.train()
            ret = result["env_runners"]["episode_return_mean"]
            if ret is not None:
                best = max(best, ret)
            if best >= 40.0:
                break
    finally:
        algo.stop()
    assert best >= 40.0, best
