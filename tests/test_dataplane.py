"""Data-plane tests: sharded shm store (multi-writer correctness, layout
guard) and the scatter-gather RPC framing (zero-copy frames, recv_into
sinks, chaos tolerance).

Store-backed tests need a loadable native lib; on machines where the
checked-in .so does not load (glibc mismatch) they skip unless
RTPU_SHM_STORE_SO points at a local build (see
.claude/skills/verify/SKILL.md).
"""

from __future__ import annotations

import multiprocessing as mp
import os
import threading
import time

import numpy as np
import pytest

from ray_tpu.core.config import GLOBAL_CONFIG as cfg


def _store_mod_or_skip():
    from ray_tpu.core import shm_store

    try:
        shm_store._load_lib()
    except OSError as e:
        pytest.skip(f"native store lib unavailable: {e}")
    return shm_store


def _oid(i: int, salt: int = 0):
    from ray_tpu.core.ids import ObjectID

    return ObjectID(bytes([salt % 256]) + i.to_bytes(8, "little") + b"\0" * 19)


# --------------------------------------------------------------------------
# store: layout guard
# --------------------------------------------------------------------------


def test_layout_version_matches():
    shm_store = _store_mod_or_skip()
    lib = shm_store._load_lib()
    assert int(lib.rtpu_lib_layout_version()) == shm_store._LAYOUT_VERSION


def test_open_missing_store_mentions_rebuild():
    shm_store = _store_mod_or_skip()
    with pytest.raises(OSError, match="layout version"):
        shm_store.ShmStore.open("/rtpu_test_definitely_missing")


# --------------------------------------------------------------------------
# store: sharded arena
# --------------------------------------------------------------------------


def test_sharded_store_basic_and_fallthrough():
    shm_store = _store_mod_or_skip()
    # 640 MB / 8 shards ~= 76 MB sub-arenas (>= the 64 MB floor).
    store = shm_store.ShmStore.create("/rtpu_test_shard", 640 << 20,
                                      prefault=False)
    try:
        assert store.n_shards > 1, "store this size should shard"
        # Objects near the sub-arena size force cross-shard fallthrough:
        # one per shard fits, a second in the same sub-arena cannot.
        nbytes = 60 << 20
        n = min(6, store.n_shards)
        payloads = {}
        for i in range(n):
            data = bytes([i * 37 % 256]) * 64
            store.put_bytes(_oid(i), [data, b"\0" * (nbytes - 64)])
            payloads[i] = data
        used, cap, n_objects, _ = store.stats()
        assert n_objects == n
        assert used >= n * nbytes
        for i in range(n):
            buf = store.get(_oid(i))
            assert buf is not None
            assert bytes(buf.buffer[:64]) == payloads[i]
            assert len(buf.buffer) == nbytes
            buf.release()
        for i in range(n):
            assert store.delete(_oid(i))
        used, _, n_objects, _ = store.stats()
        assert n_objects == 0
        assert used == 0
    finally:
        store.close()


def test_oversized_object_fails_fast_with_shard_hint():
    shm_store = _store_mod_or_skip()
    store = shm_store.ShmStore.create("/rtpu_test_big", 640 << 20,
                                      prefault=False)
    try:
        if store.n_shards < 2:
            pytest.skip("store did not shard on this config")
        t0 = time.monotonic()
        with pytest.raises(shm_store.ShmStoreFullError, match="sub-arena"):
            store.create_buffer(_oid(1), store.max_object_bytes + 1)
        # Fail-fast: no spill/evict/sleep laps for a can-never-fit object.
        assert time.monotonic() - t0 < 1.0
    finally:
        store.close()


def test_reclaim_pending_never_touches_live_objects():
    """reclaim_pending is the dead-creator rescue: it must refuse sealed
    objects, in-write (allocated) objects, and absent keys — only a true
    PENDING placeholder (unreachable from Python without a mid-create
    crash) is reclaimable."""
    shm_store = _store_mod_or_skip()
    store = shm_store.ShmStore.create("/rtpu_test_reclaim", 64 << 20,
                                      prefault=False)
    try:
        assert not store.reclaim_pending(_oid(1))  # absent
        store.put_bytes(_oid(1), b"x" * 1024)
        assert not store.reclaim_pending(_oid(1))  # sealed
        assert store.contains(_oid(1))
        mv = store.create_buffer(_oid(2), 1024)  # allocated, unsealed
        assert not store.reclaim_pending(_oid(2))
        mv[:1] = b"a"
        store.seal(_oid(2))
        assert store.contains(_oid(2))
    finally:
        store.close()


def test_small_store_collapses_to_one_shard():
    shm_store = _store_mod_or_skip()
    store = shm_store.ShmStore.create("/rtpu_test_tiny", 64 << 20,
                                      prefault=False)
    try:
        assert store.n_shards == 1
        # The full arena (minus block headers) is one allocation's limit.
        mv = store.create_buffer(_oid(7), 48 << 20)
        mv[:4] = b"abcd"
        store.seal(_oid(7))
        assert store.contains(_oid(7))
        store.delete(_oid(7))
    finally:
        store.close()


# --------------------------------------------------------------------------
# store: multi-process concurrency
# --------------------------------------------------------------------------


def _hammer_proc(store_name: str, idx: int, n_objects: int, obj_bytes: int,
                 barrier, q):
    """Writer: put own objects, read back + verify, delete half. Also read
    neighbours' objects when visible (cross-process get path)."""
    try:
        from ray_tpu.core import shm_store

        store = shm_store.ShmStore.open(store_name)
        barrier.wait(timeout=60)
        kept, deleted = [], []
        for i in range(n_objects):
            oid = _oid(i, salt=idx)
            pattern = (idx * 101 + i) % 256
            store.put_bytes(oid, [bytes([pattern]) * 64,
                                  b"\0" * (obj_bytes - 64)])
            buf = store.get(oid, timeout_ms=2000)
            assert buf is not None, f"writer {idx} lost object {i}"
            assert buf.buffer[0] == pattern
            buf.release()
            if i % 2:
                assert store.delete(oid)
                deleted.append(i)
            else:
                kept.append(i)
            # Occasionally read a neighbour's kept object (pin churn).
            if i % 7 == 3:
                nbuf = store.get(_oid(max(0, i - 2), salt=(idx + 1) % 4),
                                 timeout_ms=0)
                if nbuf is not None:
                    nbuf.release()
        # Verify every kept object survived (restore-from-spill included),
        # every deleted one reads absent (no ghosts, no resurrection).
        for i in kept:
            buf = store.get(_oid(i, salt=idx), timeout_ms=5000)
            assert buf is not None, f"writer {idx} kept object {i} is a ghost"
            assert buf.buffer[0] == (idx * 101 + i) % 256, "corrupted"
            buf.release()
        for i in deleted:
            assert not store.contains(_oid(i, salt=idx))
        q.put(("ok", idx, len(kept)))
    except BaseException as e:  # noqa: BLE001 — reported to the parent
        q.put(("err", idx, repr(e)))


def _run_hammer(k: int, n_objects: int, obj_bytes: int, capacity: int,
                name: str):
    shm_store = _store_mod_or_skip()
    store = shm_store.ShmStore.create(name, capacity, prefault=False)
    try:
        ctx = mp.get_context("spawn")
        q = ctx.Queue()
        barrier = ctx.Barrier(k)
        procs = [ctx.Process(target=_hammer_proc,
                             args=(name, i, n_objects, obj_bytes, barrier, q))
                 for i in range(k)]
        for p in procs:
            p.start()
        results = []
        deadline = time.monotonic() + 180
        while len(results) < k and time.monotonic() < deadline:
            try:
                results.append(q.get(timeout=5))
            except Exception:
                if not any(p.is_alive() for p in procs):
                    break
        for p in procs:
            p.join(timeout=30)
            assert not p.is_alive(), "hammer writer deadlocked"
        assert len(results) == k, f"only {len(results)}/{k} writers finished"
        errs = [r for r in results if r[0] != "ok"]
        assert not errs, f"writer failures: {errs}"
    finally:
        store.close()


def test_multiprocess_hammer_small():
    """4 processes x 24 x 1 MB through one 640 MB store (no pressure)."""
    _run_hammer(4, 24, 1 << 20, 640 << 20, "/rtpu_test_hammer_s")


@pytest.mark.slow
def test_multiprocess_hammer_spill_pressure():
    """4 processes x 60 x 4 MB kept-half through a 640 MB store: live
    bytes approach the arena so the spill path engages; every kept object
    must still read back byte-correct (restore) and every deleted one
    stays deleted (no ghosts)."""
    if not cfg.object_spilling_enabled:
        pytest.skip("spilling disabled in this config")
    _run_hammer(4, 60, 4 << 20, 640 << 20, "/rtpu_test_hammer_p")


# --------------------------------------------------------------------------
# protocol: scatter-gather framing (no native lib needed)
# --------------------------------------------------------------------------


class _EchoHandler:
    def __init__(self):
        self.conns = []

    def rpc_register(self, conn):
        self.conns.append(conn)
        return True

    def rpc_echo(self, conn, x):
        return x

    def rpc_chunk(self, conn, n, fill):
        import pickle

        from ray_tpu.cluster.protocol import BufferLease

        data = np.full(n, fill, np.uint8)
        return BufferLease((n, pickle.PickleBuffer(memoryview(data))),
                           lambda: None)


@pytest.fixture
def rpc_pair():
    from ray_tpu.cluster.protocol import RpcClient, RpcServer

    handler = _EchoHandler()
    server = RpcServer(handler).start()
    client = RpcClient(server.address)
    yield handler, server, client
    client.close()
    server.stop()


def test_scatter_frame_large_roundtrip(rpc_pair):
    """> 4 MB payload rides the scatter form (sendmsg of raw buffers ->
    recv_into) and round-trips byte-identically."""
    _h, _s, client = rpc_pair
    rng = np.random.default_rng(0)
    arr = rng.integers(0, 256, 6 << 20, dtype=np.uint8)
    out = client.call("echo", arr, timeout=60)
    assert isinstance(out, np.ndarray)
    assert out.nbytes == arr.nbytes
    assert np.array_equal(out, arr)
    # Mixed payload: multiple out-of-band buffers + inline smalls.
    payload = {"a": arr[: 1 << 20], "b": arr, "c": [1, "x", b"y" * 100]}
    out = client.call("echo", payload, timeout=60)
    assert np.array_equal(out["a"], arr[: 1 << 20])
    assert np.array_equal(out["b"], arr)
    assert out["c"] == [1, "x", b"y" * 100]


def test_scatter_frame_chaos_roundtrip(rpc_pair):
    """Chaos-dropped requests/responses retry to a byte-identical result
    through the scatter path."""
    _h, _s, client = rpc_pair
    arr = np.arange(5 << 17, dtype=np.int64)  # ~5 MB
    cfg.set("rpc_chaos_failure_prob", 0.3)
    try:
        out = client.retrying_call("echo", arr, timeout=10)
    finally:
        cfg.set("rpc_chaos_failure_prob", 0.0)
    assert np.array_equal(out, arr)


def test_call_into_sink_lands_bytes(rpc_pair):
    """A response buffer of exactly the sink's length lands directly in
    the caller's view (the pulled-chunk zero-staging-copy path)."""
    _h, _s, client = rpc_pair
    n = 2 << 20
    sink = bytearray(n)
    (total, data), landed = client.call_into(
        "chunk", n, 9, sink=memoryview(sink), timeout=30)
    assert landed, "response did not land in the sink"
    assert total == n
    assert sink[0] == 9 and sink[-1] == 9 and sink[n // 2] == 9
    # The decoded buffer IS the sink's memory.
    assert len(data) == n and data[0] == 9


def test_call_into_mismatched_sink_falls_back(rpc_pair):
    _h, _s, client = rpc_pair
    sink = bytearray(100)  # wrong size: reply must use its own buffer
    (total, data), landed = client.call_into(
        "chunk", 1 << 20, 5, sink=memoryview(sink), timeout=30)
    assert not landed
    assert total == 1 << 20 and len(data) == 1 << 20 and data[0] == 5
    assert bytes(sink) == b"\0" * 100


def test_client_pool_upgrades_on_push(rpc_pair):
    """Regression: a cached push-less client must gain a later caller's
    on_push (it silently dropped server pushes before)."""
    from ray_tpu.cluster.protocol import ClientPool

    handler, _s, _c = rpc_pair
    pool = ClientPool()
    try:
        first = pool.get(_s.address)  # opened WITHOUT on_push
        assert first._on_push is None
        got = []
        evt = threading.Event()

        def on_push(method, args):
            got.append((method, args))
            evt.set()

        second = pool.get(_s.address, on_push=on_push)
        assert second is first, "pool must reuse the cached client"
        assert second._on_push is on_push
        second.call("register", timeout=10)
        handler.conns[0].notify("poked", 42)
        assert evt.wait(10), "push was not delivered to the upgraded client"
        assert got == [("poked", (42,))]
    finally:
        pool.close_all()


def test_event_stats_fold_across_threads(rpc_pair):
    from ray_tpu.cluster import protocol

    _h, _s, client = rpc_pair
    before = protocol.get_event_stats().get("echo", {}).get("count", 0)
    threads = [threading.Thread(target=lambda: client.call("echo", 1,
                                                           timeout=10))
               for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    after = protocol.get_event_stats().get("echo", {}).get("count", 0)
    assert after - before == 8
