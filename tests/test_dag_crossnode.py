"""Cross-node compiled-DAG channels (reference test model: multi-node
compiled-graph tests over cross-node mutable-object channels)."""

import time

import pytest

import ray_tpu
from ray_tpu.core.task_spec import NodeAffinitySchedulingStrategy
from ray_tpu.dag import InputNode, MultiOutputNode
from ray_tpu.dag.channel import CrossNodeChannel


@pytest.fixture(scope="module")
def cluster():
    rt = ray_tpu.init(num_cpus=2)
    node = rt.add_node(num_cpus=2)
    deadline = time.time() + 30
    while time.time() < deadline:
        alive = [n for n in rt.nodes() if n["alive"]]
        if len(alive) >= 2:
            break
        time.sleep(0.25)
    yield rt, node
    ray_tpu.shutdown()


def test_dag_spans_nodes(cluster):
    """A DAG whose actors live on DIFFERENT nodes compiles with
    cross-node channels and produces correct pipelined results."""
    rt, node = cluster

    @ray_tpu.remote
    class Stage:
        def __init__(self, bias):
            self.bias = bias

        def apply(self, x):
            return x * 2 + self.bias

    # Stage A on the driver's node, stage B pinned to the second node.
    a = Stage.options(scheduling_strategy=NodeAffinitySchedulingStrategy(
        node_id=rt.node_id, soft=False)).remote(1)
    b = Stage.options(scheduling_strategy=NodeAffinitySchedulingStrategy(
        node_id=node.node_id, soft=False)).remote(10)

    with InputNode() as inp:
        mid = a.apply.bind(inp)
        out = b.apply.bind(mid)
    dag = out.experimental_compile()

    # The a->b hop and the b->driver output must be cross-node channels.
    kinds = [type(c).__name__ for c in dag._output_channels]
    assert "CrossNodeChannel" in kinds, kinds

    refs = [dag.execute(i) for i in range(12)]  # pipelined past capacity
    got = [r.get(timeout=60) for r in refs]
    assert got == [(i * 2 + 1) * 2 + 10 for i in range(12)]
    dag.teardown()


def test_dag_same_node_still_uses_shm(cluster):
    rt, _node = cluster

    @ray_tpu.remote
    class S:
        def f(self, x):
            return x + 1

    s = S.options(scheduling_strategy=NodeAffinitySchedulingStrategy(
        node_id=rt.node_id, soft=False)).remote()
    with InputNode() as inp:
        out = s.f.bind(inp)
    from ray_tpu.dag.compiled_dag import compile_dag

    dag = compile_dag(out)
    assert all(not isinstance(c, CrossNodeChannel)
               for c in dag._output_channels)
    assert dag.execute(41).get(timeout=30) == 42
    dag.teardown()
