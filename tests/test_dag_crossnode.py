"""Cross-node compiled-DAG channels (reference test model: multi-node
compiled-graph tests over cross-node mutable-object channels)."""

import time

import pytest

import ray_tpu
from ray_tpu.core.task_spec import NodeAffinitySchedulingStrategy
from ray_tpu.dag import InputNode, MultiOutputNode
from ray_tpu.dag.channel import CrossNodeChannel


@pytest.fixture(scope="module")
def cluster():
    rt = ray_tpu.init(num_cpus=2)
    node = rt.add_node(num_cpus=2)
    deadline = time.time() + 30
    while time.time() < deadline:
        alive = [n for n in rt.nodes() if n["alive"]]
        if len(alive) >= 2:
            break
        time.sleep(0.25)
    yield rt, node
    ray_tpu.shutdown()


def test_dag_spans_nodes(cluster):
    """A DAG whose actors live on DIFFERENT nodes compiles with
    cross-node channels and produces correct pipelined results."""
    rt, node = cluster

    @ray_tpu.remote
    class Stage:
        def __init__(self, bias):
            self.bias = bias

        def apply(self, x):
            return x * 2 + self.bias

    # Stage A on the driver's node, stage B pinned to the second node.
    a = Stage.options(scheduling_strategy=NodeAffinitySchedulingStrategy(
        node_id=rt.node_id, soft=False)).remote(1)
    b = Stage.options(scheduling_strategy=NodeAffinitySchedulingStrategy(
        node_id=node.node_id, soft=False)).remote(10)

    with InputNode() as inp:
        mid = a.apply.bind(inp)
        out = b.apply.bind(mid)
    dag = out.experimental_compile()

    # The a->b hop and the b->driver output must be cross-node channels.
    kinds = [type(c).__name__ for c in dag._output_channels]
    assert "CrossNodeChannel" in kinds, kinds

    refs = [dag.execute(i) for i in range(12)]  # pipelined past capacity
    got = [r.get(timeout=60) for r in refs]
    assert got == [(i * 2 + 1) * 2 + 10 for i in range(12)]
    dag.teardown()


def test_dag_same_node_still_uses_shm(cluster):
    rt, _node = cluster

    @ray_tpu.remote
    class S:
        def f(self, x):
            return x + 1

    s = S.options(scheduling_strategy=NodeAffinitySchedulingStrategy(
        node_id=rt.node_id, soft=False)).remote()
    with InputNode() as inp:
        out = s.f.bind(inp)
    from ray_tpu.dag.compiled_dag import compile_dag

    dag = compile_dag(out)
    assert all(not isinstance(c, CrossNodeChannel)
               for c in dag._output_channels)
    assert dag.execute(41).get(timeout=30) == 42
    dag.teardown()


def test_dag_overlap_comm_subprocess():
    """The sender-thread path (dag_overlap_comm=1) runs the full cross-
    node pipeline correctly — exercised in a subprocess because workers
    read the flag from their spawn environment."""
    import subprocess
    import sys

    code = """
import os, sys, time, collections
sys.path.insert(0, %r)
import ray_tpu
from ray_tpu.core.task_spec import NodeAffinitySchedulingStrategy
from ray_tpu.dag import InputNode
rt = ray_tpu.init(num_cpus=2)
node = rt.add_node(num_cpus=2)
deadline = time.time() + 30
while time.time() < deadline and len(
        [n for n in rt.nodes() if n["alive"]]) < 2:
    time.sleep(0.25)

@ray_tpu.remote
class S:
    def f(self, x):
        return x + 1

a = S.options(scheduling_strategy=NodeAffinitySchedulingStrategy(
    node_id=rt.node_id, soft=False)).remote()
b = S.options(scheduling_strategy=NodeAffinitySchedulingStrategy(
    node_id=node.node_id, soft=False)).remote()
with InputNode() as inp:
    out = b.f.bind(a.f.bind(inp))
dag = out.experimental_compile()
w = collections.deque()
got = []
for i in range(30):
    w.append(dag.execute(i))
    if len(w) >= 4:
        got.append(w.popleft().get(timeout=60))
while w:
    got.append(w.popleft().get(timeout=60))
assert got == [i + 2 for i in range(30)], got[:5]
dag.teardown()
ray_tpu.shutdown()
print("OVERLAP_OK")
"""
    import os as _os

    repo = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
    env = dict(_os.environ, RTPU_DAG_OVERLAP_COMM="1",
               JAX_PLATFORMS="cpu")
    out = subprocess.run([sys.executable, "-c", code % repo],
                         capture_output=True, text=True, timeout=180,
                         env=env)
    assert "OVERLAP_OK" in out.stdout, out.stderr[-800:]
