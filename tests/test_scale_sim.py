"""Simulated-N-node scale mode + the head's indexed hot-path
structures (node->objects reverse index, cached per-node utilization)
— in-process, no store, tier-1 everywhere.
"""

from __future__ import annotations

import time

from ray_tpu.cluster.head import HeadServer
from ray_tpu.core.cluster_runtime import SimulatedCluster
from ray_tpu.core.config import GLOBAL_CONFIG as cfg


def test_simulated_cluster_control_plane_end_to_end():
    """8 simulated nodes register, heartbeat, serve picks/locations/
    census/drain — the full control-plane surface bench.py --scale
    profiles at 100."""
    sim = SimulatedCluster(8, resources={"CPU": 4.0})
    try:
        sim.wait_registered(30)
        views = sim.client.call("list_nodes", timeout=10)
        assert sum(1 for v in views if v["alive"]) == 8
        # Scheduling works against simulated nodes.
        picked = sim.client.call("pick_node", {"CPU": 1.0}, None, None,
                                 "sim-k", timeout=10)
        assert picked is not None
        # Directory: seed via the batched wire shape, look up, drain.
        nid = sim.nodes[0].node_id
        oid = b"x" * 28
        sim.client.call("object_batch", nid, [("add", oid, 123)],
                        timeout=10)
        locs = sim.client.call("object_locations", oid, None, timeout=10)
        assert [l[0] for l in locs] == [nid]
        census = sim.client.call("cluster_leases", timeout=30)
        assert len(census) == 8
        assert all("error" not in v for v in census.values()
                   if isinstance(v, dict))
        sim.client.call("drain_node", nid, timeout=10)
        assert sim.client.call("object_locations", oid, None,
                               timeout=10) == []
    finally:
        sim.shutdown()


def test_simulated_node_spawns_no_worker_machinery():
    sim = SimulatedCluster(1)
    try:
        sim.wait_registered(15)
        n = sim.nodes[0]
        assert n.simulated
        assert n._workers == {}
        assert n._zygote is None
        assert n._metrics_exporter is None
        # The stubbed store serves the control-plane calls it needs.
        assert n.store.contains(object()) is False
        assert n.store.stats() == (0, 0, 0, 0)
    finally:
        sim.shutdown()


def test_head_reverse_index_tracks_adds_removes_and_death():
    """The node->objects reverse index must stay consistent with the
    holder-set directory through every mutation path — it is what node
    death/drain scrubs instead of walking the full table."""
    head = HeadServer()
    try:
        head.rpc_register_node(None, "nA", "127.0.0.1:1", {"CPU": 1}, {},
                               "sA")
        head.rpc_register_node(None, "nB", "127.0.0.1:2", {"CPU": 1}, {},
                               "sB")
        o1, o2 = b"a" * 28, b"b" * 28
        head.rpc_object_added(None, o1, "nA", 10)
        head.rpc_object_batch(None, "nB", [("add", o1, 10),
                                           ("add", o2, 20)])
        assert head._node_objects["nA"] == {o1}
        assert head._node_objects["nB"] == {o1, o2}
        # Removal via both wire shapes.
        head.rpc_object_removed(None, o1, "nA")
        assert head._node_objects["nA"] == set()
        assert head._object_dir[o1] == {"nB"}
        # Death scrub drops ONLY the dead node's entries.
        head._on_node_dead("nB")
        assert "nB" not in head._node_objects
        assert o1 not in head._object_dir
        assert o2 not in head._object_dir
        assert head._object_sizes == {}
    finally:
        head.shutdown()


def test_node_util_cache_tracks_heartbeats():
    """pick scoring reads the cached util; heartbeats (full and delta)
    must keep it fresh."""
    head = HeadServer()
    try:
        head.rpc_register_node(None, "nA", "127.0.0.1:1",
                               {"CPU": 4.0, "TPU": 2.0}, {}, "sA")
        n = head._nodes["nA"]
        assert n.util == 0.0
        assert head.rpc_heartbeat(None, "nA", {"CPU": 2.0, "TPU": 2.0},
                                  version=1, is_delta=False) is True
        assert n.util == 0.5
        # Delta carrying only the changed resource.
        assert head.rpc_heartbeat(None, "nA", {"TPU": 0.0},
                                  version=2, is_delta=True) is True
        assert n.util == 1.0
        # Empty delta (nothing changed): cheap, util untouched.
        assert head.rpc_heartbeat(None, "nA", {}, version=3,
                                  is_delta=True) is True
        assert n.util == 1.0
        # The pick path consumes the cache: a fully-used node loses to
        # an idle one.
        head.rpc_register_node(None, "nB", "127.0.0.1:2",
                               {"CPU": 4.0, "TPU": 2.0}, {}, "sB")
        picked = head.rpc_pick_node(None, {"CPU": 1.0})
        assert picked[0] == "nB"
    finally:
        head.shutdown()


def test_prepare_upgrade_drains_and_reports():
    head = HeadServer()
    try:
        head.rpc_register_node(None, "nA", "127.0.0.1:1", {"CPU": 1}, {},
                               "sA")
        summary = head.rpc_prepare_upgrade(None)
        assert summary["incarnation"] == head.incarnation
        assert summary["nodes"] == 1
        assert summary["flushed"] is False  # memory-only head
        assert head._draining
        # Draining head stops issuing death verdicts: a node with an
        # ancient heartbeat survives the sweep.
        head._nodes["nA"].last_heartbeat = time.monotonic() - 3600
        head._sweep_alive_watch()  # no-op either way; the health loop
        # itself is gated on _draining (exercised via the flag).
        assert head.rpc_resume_serving(None) is True
        assert not head._draining
    finally:
        head.shutdown()


def test_recovered_alive_actor_watch_grace(tmp_path):
    """A head restarted from sqlite with an ALIVE actor whose node never
    re-registers must declare it dead after the grace window and
    re-drive it (the all-holders-dead shape, unit tier)."""
    from ray_tpu.cluster.head import ALIVE, RESTARTING, DEAD, ActorInfo

    db = str(tmp_path / "head.db")
    head = HeadServer(persist_path=db)
    aid = b"actor-000"
    try:
        info = ActorInfo(aid, None, "default", b"\x80\x04N.", 1, {},
                         max_task_retries=-1)
        info.state = ALIVE
        info.node_id = "gone-node"
        info.worker_addr = "127.0.0.1:9"
        head._actors[aid] = info
        head._persist_actor(info)
    finally:
        head.shutdown()
    old = cfg.head_restart_actor_grace_s
    cfg.set("head_restart_actor_grace_s", 0.5)
    try:
        head2 = HeadServer(persist_path=db)
        try:
            assert aid in head2._alive_watch
            info2 = head2._actors[aid]
            assert info2.state == ALIVE
            assert info2.max_task_retries == -1  # persisted policy
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline and aid in head2._alive_watch:
                time.sleep(0.1)
            # Grace expired with no node: re-driven through max_restarts
            # (no node to land on here, so it parks RESTARTING and then
            # fails -> DEAD; the point is it LEFT the zombie-ALIVE state).
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline and \
                    head2._actors[aid].state == ALIVE:
                time.sleep(0.1)
            assert head2._actors[aid].state in (RESTARTING, DEAD)
        finally:
            head2.shutdown()
    finally:
        cfg.set("head_restart_actor_grace_s", old)


def test_recovered_alive_actor_confirmed_when_node_returns(tmp_path):
    """The inverse: the host node re-registers inside the grace window
    and the actor is confirmed, never killed."""
    from ray_tpu.cluster.head import ALIVE, ActorInfo

    db = str(tmp_path / "head.db")
    head = HeadServer(persist_path=db)
    aid = b"actor-001"
    try:
        info = ActorInfo(aid, None, "default", b"\x80\x04N.", 1, {})
        info.state = ALIVE
        info.node_id = "node-back"
        head._actors[aid] = info
        head._persist_actor(info)
    finally:
        head.shutdown()
    head2 = HeadServer(persist_path=db)
    try:
        assert aid in head2._alive_watch
        head2.rpc_register_node(None, "node-back", "127.0.0.1:3",
                                {"CPU": 1}, {}, "s")
        head2._sweep_alive_watch()
        assert aid not in head2._alive_watch
        assert head2._actors[aid].state == ALIVE
    finally:
        head2.shutdown()
