"""Train-lite integration: worker gang, report lockstep, checkpoint/resume,
failure restart (SURVEY M6; reference test model:
python/ray/train/tests/test_data_parallel_trainer.py).

Runs against a real in-process cluster (worker subprocesses) with the tiny
Llama on CPU JAX — no TPU required.
"""

import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu.train import (Checkpoint, CheckpointConfig, FailureConfig,
                           JaxTrainer, RunConfig, ScalingConfig)


@pytest.fixture(scope="module")
def cluster():
    rt = ray_tpu.init(num_cpus=4)
    yield rt
    ray_tpu.shutdown()


def _tiny_llama_loop(config):
    """Per-worker loop: trains tiny Llama, checkpoints pytrees, resumes."""
    import tempfile

    import jax
    import numpy as np

    import ray_tpu.train as train
    from ray_tpu.models import llama
    from ray_tpu.parallel import spmd
    from ray_tpu.parallel.mesh import MeshSpec, make_mesh, mesh_context

    ctx = train.get_context()
    assert ctx.get_world_size() == config["world_size"]

    cfg = llama.tiny_config()
    mesh = make_mesh(MeshSpec(), jax.devices("cpu")[:1])
    tx = spmd.default_optimizer(lr=1e-2)
    with mesh_context(mesh):
        state = spmd.sharded_init(cfg, mesh, jax.random.PRNGKey(0), tx)
        start_step = 0
        ckpt = train.get_checkpoint()
        if ckpt is not None:
            with ckpt.as_directory() as d:
                state = train.load_pytree(d)
                start_step = int(state.step)
        step_fn = spmd.make_train_step(cfg, mesh, tx)
        rng = np.random.default_rng(ctx.get_world_rank())
        for i in range(start_step, config["num_steps"]):
            tokens = rng.integers(0, cfg.vocab_size, (2, 64)).astype(np.int32)
            state, metrics = step_fn(state, tokens)
            if config.get("fail_at") == i and ckpt is None:
                raise RuntimeError("injected worker failure")
            payload = {"loss": float(metrics["loss"]), "step": i,
                       "start_step": start_step,
                       "rank": ctx.get_world_rank()}
            if (i + 1) % config["checkpoint_every"] == 0 \
                    and ctx.get_world_rank() == 0:
                d = tempfile.mkdtemp(prefix="rtpu_test_ckpt_")
                train.save_pytree(jax.device_get(state), d)
                train.report(payload, checkpoint=Checkpoint(d))
            else:
                train.report(payload)


def test_train_e2e_checkpoint_and_resume(cluster, tmp_path):
    run = RunConfig(name="tiny", storage_path=str(tmp_path),
                    checkpoint_config=CheckpointConfig(num_to_keep=2))
    trainer = JaxTrainer(
        _tiny_llama_loop,
        train_loop_config={"num_steps": 6, "checkpoint_every": 2,
                           "world_size": 2},
        scaling_config=ScalingConfig(num_workers=2),
        run_config=run,
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics is not None and result.metrics["step"] == 5
    assert result.checkpoint is not None
    assert len(result.metrics_dataframe) == 6          # 6 lockstep rounds
    # top-k retention: only 2 checkpoint dirs remain of the 3 registered
    ckpts = [n for n in os.listdir(result.path) if n.startswith("checkpoint_")]
    assert len(ckpts) == 2

    # Resume: new run, same storage -> starts from the saved step, not 0.
    trainer2 = JaxTrainer(
        _tiny_llama_loop,
        train_loop_config={"num_steps": 8, "checkpoint_every": 2,
                           "world_size": 2},
        scaling_config=ScalingConfig(num_workers=2),
        run_config=run,
    )
    result2 = trainer2.fit()
    assert result2.error is None
    # checkpoint_every=2, num_steps=6 -> latest checkpoint is post-step-5
    # (state.step == 6), so the resumed run reports starting there.
    assert result2.metrics["start_step"] == 6
    assert result2.metrics["step"] == 7


def test_train_failure_restarts_from_checkpoint(cluster, tmp_path):
    run = RunConfig(name="faulty", storage_path=str(tmp_path),
                    failure_config=FailureConfig(max_failures=1))
    trainer = JaxTrainer(
        _tiny_llama_loop,
        train_loop_config={"num_steps": 5, "checkpoint_every": 2,
                           "world_size": 1, "fail_at": 3},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=run,
    )
    result = trainer.fit()
    # Attempt 1 checkpoints after steps 1 and 3... fails AT step 3 before
    # reporting; attempt 2 resumes from the step-1 checkpoint (state.step=2)
    # and, now resuming (ckpt present), runs to completion.
    assert result.error is None
    assert result.metrics["step"] == 4
    assert result.metrics["start_step"] == 2


def test_train_failure_budget_exhausted(cluster, tmp_path):
    def always_fail(config):
        raise ValueError("boom")

    trainer = JaxTrainer(
        always_fail,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="doomed", storage_path=str(tmp_path),
                             failure_config=FailureConfig(max_failures=0)),
    )
    result = trainer.fit()
    assert result.error is not None
    assert "boom" in str(result.error)


def test_worker_group_execute(cluster):
    from ray_tpu.train import WorkerGroup

    g = WorkerGroup(ScalingConfig(num_workers=2))
    g.start()
    try:
        outs = g.execute(lambda: os.getpid())
        assert len(outs) == 2 and outs[0] != outs[1]  # distinct processes
    finally:
        g.shutdown()
