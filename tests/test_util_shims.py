"""multiprocessing.Pool shim + joblib backend + collective p2p
(reference test model: python/ray/tests/test_multiprocessing.py,
util/joblib tests, util/collective p2p tests)."""

import numpy as np
import pytest

import ray_tpu


@pytest.fixture(scope="module")
def cluster():
    rt = ray_tpu.init(num_cpus=4)
    yield rt
    ray_tpu.shutdown()


def _sq(x):
    return x * x


def _addmul(a, b):
    return a * 10 + b


def test_pool_map_variants(cluster):
    from ray_tpu.util.multiprocessing import Pool

    with Pool(processes=4) as p:
        assert p.map(_sq, range(20)) == [i * i for i in range(20)]
        assert p.starmap(_addmul, [(1, 2), (3, 4)]) == [12, 34]
        assert list(p.imap(_sq, range(10), chunksize=3)) == [
            i * i for i in range(10)]
        assert sorted(p.imap_unordered(_sq, range(10), chunksize=2)) == \
            sorted(i * i for i in range(10))
        r = p.apply_async(_addmul, (5, 6))
        assert r.get(timeout=60) == 56
        assert p.apply(_sq, (9,)) == 81
    with pytest.raises(ValueError):
        p.map(_sq, [1])  # closed


def test_joblib_backend(cluster):
    joblib = pytest.importorskip("joblib")
    from ray_tpu.util.joblib_backend import register_ray_tpu

    register_ray_tpu()
    with joblib.parallel_backend("ray_tpu", n_jobs=4):
        out = joblib.Parallel()(joblib.delayed(_sq)(i) for i in range(12))
    assert out == [i * i for i in range(12)]


def test_collective_p2p_send_recv(cluster):
    from ray_tpu.util import collective as col

    @ray_tpu.remote(num_cpus=0)
    class Peer:
        def __init__(self, rank):
            col.init_collective_group(2, rank, "p2p-gang")
            self.rank = rank

        def run(self):
            if self.rank == 0:
                col.send(np.arange(4.0), 1, "p2p-gang", tag=7)
                return col.recv(1, "p2p-gang", tag=8).tolist()
            got = col.recv(0, "p2p-gang", tag=7)
            col.send(got * 2, 0, "p2p-gang", tag=8)
            return got.tolist()

    peers = [Peer.remote(i) for i in range(2)]
    r0, r1 = ray_tpu.get([p.run.remote() for p in peers], timeout=120)
    assert r1 == [0.0, 1.0, 2.0, 3.0]
    assert r0 == [0.0, 2.0, 4.0, 6.0]
    for p in peers:
        ray_tpu.kill(p)
    ray_tpu.kill(ray_tpu.get_actor("rtpu-collective-p2p-gang"))


def test_collective_p2p_same_tag_queues(cluster):
    """Back-to-back sends with ONE tag queue FIFO (no clobber/hang)."""
    from ray_tpu.util import collective as col

    @ray_tpu.remote(num_cpus=0)
    class P:
        def __init__(self, rank):
            col.init_collective_group(2, rank, "fifo-gang")
            self.rank = rank

        def run(self):
            if self.rank == 0:
                for i in range(4):
                    col.send(np.array([i]), 1, "fifo-gang")
                return True
            return [int(col.recv(0, "fifo-gang")[0]) for _ in range(4)]

    a, b = P.remote(0), P.remote(1)
    ok, got = ray_tpu.get([a.run.remote(), b.run.remote()], timeout=120)
    assert got == [0, 1, 2, 3]
    for p in (a, b):
        ray_tpu.kill(p)
    ray_tpu.kill(ray_tpu.get_actor("rtpu-collective-fifo-gang"))


def test_pool_bounds_inflight_and_empty(cluster):
    from ray_tpu.util.multiprocessing import Pool

    with Pool(processes=2) as p:
        # Empty iterable: immediately-ready empty result (stdlib shape).
        r = p.map_async(_sq, [])
        assert r.ready() and r.get(timeout=10) == []
        # successful() raises while pending (stdlib contract).
        slow = p.apply_async(__import__("time").sleep, (1.5,))
        import pytest as _pytest

        if not slow.ready():
            with _pytest.raises(ValueError):
                slow.successful()
        slow.wait(timeout=30)
        # Windowed submission: in-flight never exceeds `processes`.
        res = p.map_async(_sq, range(40), chunksize=1)
        res._pump(block=False)
        assert len(res._refs) <= 2
        assert res.get(timeout=120) == [i * i for i in range(40)]
