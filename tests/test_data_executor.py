"""Streaming Dataset executor tier: operator graph construction, bounded
inter-operator queues, and the channel data plane under map stages and
shuffles (reference test model: python/ray/data/tests/
test_streaming_executor.py, test_backpressure_policies.py,
test_streaming_fault_tolerance.py).

The top half is store-free (plan rewriting is pure, queues ride mmap
rings); the cluster half skips cleanly where the native store lib can't
boot a cluster.
"""

import threading
import time
import uuid

import numpy as np
import pytest

import ray_tpu
import ray_tpu.data as rdata
from ray_tpu.core.config import GLOBAL_CONFIG as cfg
from ray_tpu.data._exchange import merge_pieces, partition_rows
from ray_tpu.data._executor import (ChannelMapStage, adapt_plan,
                                    describe_physical)
from ray_tpu.data._queues import ChannelQueue, LocalQueue, QueueStopped
from ray_tpu.data._streaming import (ExecContext, InputOperator,
                                     LimitOperator, optimize_plan)
from ray_tpu.dag.ring import RingChannel


# ------------------------------------------------- physical plan (store-free)

def test_adapt_plan_builds_channel_stages():
    ds = (rdata.range(32)
          .map_batches(lambda b: {"id": b["id"] * 2})
          .map_batches(lambda b: {"id": b["id"] + 1})
          .map_batches(lambda b: {"id": b["id"] * 10}))
    ops = adapt_plan(optimize_plan(ds._ops))
    stages = [op for op in ops if isinstance(op, ChannelMapStage)]
    # Fusion happened BEFORE the physical rewrite: one lane fleet runs
    # the whole fused chain, not one per map.
    assert len(stages) == 1
    assert len(stages[0].payload["stages"]) == 3
    assert stages[0].lanes >= 1
    desc = describe_physical(ops)
    assert desc.startswith("channel_map[") and "+" in desc, desc


def test_limit_pushdown_survives_adapt():
    ds = rdata.range(100).map(lambda r: {"id": r["id"] * 3}).limit(5)
    ops = adapt_plan(optimize_plan(ds._ops))
    kinds = [type(op).__name__ for op in ops]
    # The pushed-down limit stays a driver op, BELOW (before) the map.
    assert kinds.index("LimitOperator") < kinds.index("ChannelMapStage")
    assert any(isinstance(op, LimitOperator) for op in ops)


def test_actor_pool_op_becomes_channel_stage():
    class AddBias:
        def __init__(self, bias):
            self.bias = bias

        def __call__(self, b):
            return {"id": b["id"] + self.bias}

    ds = rdata.range(16).map_batches(AddBias, fn_constructor_kwargs={
        "bias": 5}, concurrency=(2, 4))
    ops = adapt_plan(optimize_plan(ds._ops))
    stages = [op for op in ops if isinstance(op, ChannelMapStage)]
    assert len(stages) == 1
    assert stages[0].payload["fn_cls"] is AddBias
    assert 2 <= stages[0].lanes <= 4


# ------------------------------------------------------ queues (store-free)

def test_local_queue_blocks_producer_at_capacity():
    q = LocalQueue(capacity=2, name="t")
    q.put(1)
    q.put(2)
    progressed = threading.Event()

    def produce():
        q.put(3)  # must block until the consumer frees a slot
        progressed.set()

    t = threading.Thread(target=produce, daemon=True)
    t.start()
    assert not progressed.wait(0.3), "producer ran past a full queue"
    assert q.get() == 1
    assert progressed.wait(5.0), "producer never unblocked"
    assert q.get() == 2 and q.get() == 3
    q.shutdown()


def test_local_queue_stop_drains_then_raises():
    q = LocalQueue(capacity=4, name="t")
    q.put("a")
    q.put_stop()
    assert q.get() == "a"  # backlog drains before the stop marker
    with pytest.raises(QueueStopped):
        q.get()
    q.shutdown()


def test_local_queue_shutdown_unblocks_producer():
    q = LocalQueue(capacity=1, name="t")
    q.put(1)
    done = threading.Event()

    def produce():
        q.put(2)  # consumer abandons: put must return, not hang
        done.set()

    t = threading.Thread(target=produce, daemon=True)
    t.start()
    time.sleep(0.1)
    q.shutdown()
    assert done.wait(5.0)


def test_channel_queue_ring_backpressure():
    """The executor's edge contract on a real shm ring: capacity bounds
    frames in flight, a slow consumer blocks the producer, stop ends the
    stream."""
    cid = uuid.uuid4().bytes[:12]
    wq = ChannelQueue(RingChannel(cid, capacity=2), name="w")
    rq = ChannelQueue(RingChannel(cid, capacity=2), name="r")
    try:
        rq.prepare_read()
        wq.put((0, "a"))
        wq.put((1, "b"))
        progressed = threading.Event()

        def produce():
            wq.put((2, "c"), timeout=30.0)  # ring full: must block here
            wq.put_stop()
            progressed.set()

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        assert not progressed.wait(0.3), "producer ran past a full ring"
        assert rq.get(timeout=5.0) == (0, "a")  # frees a slot
        assert rq.get(timeout=5.0) == (1, "b")
        assert rq.get(timeout=5.0) == (2, "c")
        assert progressed.wait(5.0), "producer never unblocked"
        with pytest.raises(QueueStopped):
            rq.get(timeout=5.0)
        t.join(timeout=5.0)
    finally:
        wq.shutdown()
        rq.shutdown(unlink=True)


# --------------------------------------- exchange kernels (store-free)

def _blocks(seed, n_blocks=6, rows=40):
    rng = np.random.default_rng(seed)
    return [{"k": rng.integers(0, 17, rows), "v": rng.integers(0, 1000, rows)}
            for _ in range(n_blocks)]


def test_exchange_kernels_transport_order_identity():
    """Both transports share partition_rows/merge_pieces; the channel
    path's only freedom is piece ARRIVAL order. Reducers re-sort pieces
    by block index, so any interleaving merges identically to the task
    path's in-order waves."""
    blocks = _blocks(7)
    n_parts = 5

    def assign(block, block_index):
        return np.asarray(block["k"]) % n_parts

    split = [partition_rows(b, assign, n_parts, i)
             for i, b in enumerate(blocks)]
    # Task transport: partition j's pieces in block order.
    task_out = [merge_pieces([split[i][j] for i in range(len(blocks))],
                             None) for j in range(n_parts)]
    # Channel transport: pieces land interleaved across 3 mappers; the
    # reducer keys them by block index and sorts before merging.
    for j in range(n_parts):
        cells = {}
        for m in range(3):
            for i in range(m, len(blocks), 3):  # mapper m's stream
                cells[i] = split[i][j]
        chan = merge_pieces([cells[i] for i in sorted(cells)], None)
        assert np.array_equal(chan["k"], task_out[j]["k"])
        assert np.array_equal(chan["v"], task_out[j]["v"])


def test_partition_rows_empty_block_keeps_schema():
    empty = {"k": np.array([], dtype=np.int64)}
    parts = partition_rows(empty, lambda b, i: np.array([]), 3)
    assert len(parts) == 3
    assert all(p["k"].shape == (0,) for p in parts)


def test_train_session_iter_device_batches_delegates():
    """The train-surface ingest helper hands the shard's iter_batches the
    device + prefetch depth (the double-buffered path); plain-sequence
    shards without iter_batches are rejected up front."""
    from ray_tpu.train.config import TrainContextConfig
    from ray_tpu.train.session import TrainSession

    class FakeShard:
        def __init__(self):
            self.calls = []

        def iter_batches(self, **kw):
            self.calls.append(kw)
            return iter([{"x": np.ones(2)}])

    shard = FakeShard()
    sess = TrainSession(lambda cfg: None, {}, TrainContextConfig(),
                        dataset_shards={"train": shard, "plain": [1, 2, 3]})
    out = list(sess.iter_device_batches(
        batch_size=32, device="dev0", prefetch_depth=4))
    assert len(out) == 1
    assert shard.calls == [{"batch_size": 32, "device_put": "dev0",
                            "prefetch_depth": 4}]
    with pytest.raises(TypeError):
        sess.iter_device_batches("plain", device="dev0")
    with pytest.raises(KeyError):
        sess.iter_device_batches("missing", device="dev0")


# ------------------------------------------------------------ cluster tier

@pytest.fixture(scope="module")
def cluster():
    try:
        rt = ray_tpu.init(num_cpus=4)
    except Exception as e:
        try:
            ray_tpu.shutdown()
        except Exception:
            pass
        pytest.skip(f"cluster runtime unavailable: {e!r}")
    yield rt
    ray_tpu.shutdown()


def test_streaming_matches_pull_executor(cluster):
    ds = (rdata.range(200, parallelism=8)
          .map_batches(lambda b: {"v": b["id"] * 3})
          .filter(lambda r: r["v"] % 2 == 0))
    old = cfg.data_executor
    try:
        cfg.data_executor = "pull"
        pull_rows = [r["v"] for r in ds.take_all()]
        cfg.data_executor = "streaming"
        stream_rows = [r["v"] for r in ds.take_all()]
    finally:
        cfg.data_executor = old
    assert stream_rows == pull_rows


def test_channel_vs_task_shuffle_identity(cluster):
    ds = rdata.range(300, parallelism=6).map_batches(
        lambda b: {"v": b["id"] * 7})
    old = cfg.data_exchange_transport
    try:
        cfg.data_exchange_transport = "channel"
        a = [r["v"] for r in ds.random_shuffle(seed=11).take_all()]
        cfg.data_exchange_transport = "task"
        b = [r["v"] for r in ds.random_shuffle(seed=11).take_all()]
    finally:
        cfg.data_exchange_transport = old
    assert a == b
    assert sorted(a) == [i * 7 for i in range(300)]


def test_channel_vs_task_sort_identity(cluster):
    ds = rdata.range(200, parallelism=5).map_batches(
        lambda b: {"k": (b["id"] * 37) % 41, "v": b["id"]})
    old = cfg.data_exchange_transport
    try:
        cfg.data_exchange_transport = "channel"
        a = [(r["k"], r["v"]) for r in ds.sort("k").take_all()]
        cfg.data_exchange_transport = "task"
        b = [(r["k"], r["v"]) for r in ds.sort("k").take_all()]
    finally:
        cfg.data_exchange_transport = old
    assert a == b
    assert a == sorted(a, key=lambda t: t[0])


def _slow_triple(b):
    time.sleep(0.2)  # keep lanes mid-stream long enough to kill one
    return {"v": b["id"] * 3}


def test_lane_death_mid_stream_recovers(cluster):
    """Kill one operator actor while its stage is mid-stream: the driver
    respawns the lane, replays its in-flight frames, and the output is
    row-identical to an undisturbed run."""
    ds = rdata.range(64, parallelism=8).map_batches(_slow_triple)
    expected = [r["v"] for r in ds.take_all()]

    ops = adapt_plan(optimize_plan(ds._ops))
    stage = next(op for op in ops if isinstance(op, ChannelMapStage))
    ctx = ExecContext()
    stream = InputOperator(ds._read_tasks, parallelism=8).execute(None, ctx)
    out = stage.execute(stream, ctx)
    got = []
    try:
        ref, _meta = next(out)
        got.extend(ray_tpu.get(ref)["v"].tolist())
        ray_tpu.kill(stage._live_lanes[0].actor)  # mid-stream death
        for ref, _meta in out:
            got.extend(ray_tpu.get(ref)["v"].tolist())
    finally:
        ctx.run_finalizers()
    assert got == expected
    assert any(lane.respawns for lane in stage._live_lanes)
