"""Pipeline parallelism (pp) and MoE expert parallelism (ep) — the two
mesh axes declared in parallel/mesh.py, exercised on the 8-CPU mesh.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import llama, mixtral
from ray_tpu.parallel import pipeline, spmd
from ray_tpu.parallel.mesh import (MeshSpec, make_mesh, mesh_context,
                                   param_shardings)


@pytest.fixture(scope="module")
def pp2_mesh():
    return make_mesh(MeshSpec(pp=2, fsdp=2, tp=2), jax.devices("cpu")[:8])


def test_pipeline_matches_dense_forward(pp2_mesh):
    """GPipe is a schedule, not an approximation: same weights => same
    loss as the plain sequential forward."""
    cfg = llama.tiny_config(n_layers=4)
    key = jax.random.PRNGKey(0)
    params = llama.init_params(cfg, key)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (4, 32)),
        jnp.int32)

    dense_loss, _ = jax.jit(
        lambda p, t: llama.loss_fn(p, t, cfg))(params, tokens)

    pcfg = pipeline.PipelineConfig(stages=2, microbatches=4)
    staged = pipeline.stage_params(params, 2)
    with mesh_context(pp2_mesh):
        pipe_loss, _ = jax.jit(
            lambda p, t: pipeline.pipeline_loss_fn(p, t, cfg, pcfg,
                                                   mesh=pp2_mesh))(
            staged, tokens)
    np.testing.assert_allclose(float(pipe_loss), float(dense_loss),
                               rtol=2e-4)


@pytest.mark.slow  # tier-1 budget relief (PR 12): 26.5s measured on a quiet box;
# convergence smoke — pipeline step shape/math stays tier-1
def test_pipeline_train_step_decreases_loss(pp2_mesh):
    cfg = llama.tiny_config(n_layers=4)
    pcfg = pipeline.PipelineConfig(stages=2, microbatches=4)
    tx = spmd.default_optimizer(lr=5e-3, warmup=0, decay_steps=100)
    with mesh_context(pp2_mesh):
        params = pipeline.stage_params(
            llama.init_params(cfg, jax.random.PRNGKey(0)), 2)
        shardings = param_shardings(
            pp2_mesh, pipeline.pipeline_param_logical_axes(cfg))
        params = jax.device_put(params, shardings)
        state = spmd.TrainState(jnp.zeros((), jnp.int32), params,
                                jax.jit(tx.init)(params))
        step = pipeline.make_pipeline_train_step(cfg, pcfg, pp2_mesh, tx)
        tokens = jnp.asarray(
            np.random.default_rng(1).integers(0, cfg.vocab_size, (4, 32)),
            jnp.int32)
        losses = []
        for _ in range(8):
            state, metrics = step(state, tokens)
            losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0], losses


def test_pipeline_validation_errors():
    cfg = llama.tiny_config(n_layers=4)
    with pytest.raises(ValueError, match="not divisible"):
        pipeline.PipelineConfig(3, 4).validate(cfg, 8)
    with pytest.raises(ValueError, match="microbatches >= stages"):
        pipeline.PipelineConfig(2, 1).validate(cfg, 2)


# ---------------------------------------------------------------- mixtral

def test_moe_capacity_dispatch_math():
    """Under-capacity regime: the dispatched FFN must equal the dense
    gate-weighted mixture of expert FFNs."""
    cfg = mixtral.tiny_moe_config(capacity_factor=8.0)  # no drops
    key = jax.random.PRNGKey(0)
    params = mixtral.init_params(cfg, key)
    layer0 = jax.tree_util.tree_map(lambda v: v[0], params["blocks"])
    x = jax.random.normal(jax.random.fold_in(key, 1), (2, 8, cfg.d_model),
                          jnp.float32)

    out, aux = mixtral.moe_ffn(x, layer0, cfg)

    # Dense reference: run every expert on every token; combine by gates.
    xt = x.reshape(-1, cfg.d_model)
    logits = xt @ layer0["w_router"]
    probs = jax.nn.softmax(logits, axis=-1)
    gv, gi = jax.lax.top_k(probs, cfg.top_k)
    gv = gv / gv.sum(-1, keepdims=True)
    dense = np.zeros_like(xt)
    for e in range(cfg.n_experts):
        h = np.asarray(jax.nn.silu(xt @ layer0["w_gate"][e])
                       * (xt @ layer0["w_up"][e]) @ layer0["w_down"][e])
        for k in range(cfg.top_k):
            sel = np.asarray(gi[:, k] == e)
            dense[sel] += np.asarray(gv[:, k])[sel, None] * h[sel]
    np.testing.assert_allclose(np.asarray(out).reshape(-1, cfg.d_model),
                               dense, rtol=2e-4, atol=2e-5)
    assert float(aux) > 0


def test_moe_overflow_drops_are_bounded():
    """capacity_factor=0 (degenerate) still keeps top_k slots per expert;
    dropped tokens contribute zero (residual carries them)."""
    cfg = mixtral.tiny_moe_config(capacity_factor=0.01)
    params = mixtral.init_params(cfg, jax.random.PRNGKey(0))
    layer0 = jax.tree_util.tree_map(lambda v: v[0], params["blocks"])
    x = jax.random.normal(jax.random.PRNGKey(2), (2, 16, cfg.d_model),
                          jnp.float32)
    out, _ = mixtral.moe_ffn(x, layer0, cfg)
    assert np.isfinite(np.asarray(out)).all()


@pytest.mark.slow  # tier-1 budget relief (PR 12): 17.7s measured on a quiet box;
# EP-mesh train smoke — MoE dispatch math stays tier-1
def test_mixtral_train_step_ep_mesh():
    """End-to-end MoE training over an ep-sharded mesh."""
    import optax

    mesh = make_mesh(MeshSpec(ep=4, fsdp=2), jax.devices("cpu")[:8])
    cfg = mixtral.tiny_moe_config()
    tx = optax.adam(3e-3)
    with mesh_context(mesh):
        shardings = param_shardings(mesh, mixtral.param_logical_axes(cfg))
        params = jax.device_put(
            mixtral.init_params(cfg, jax.random.PRNGKey(0)), shardings)
        opt_state = jax.jit(tx.init)(params)

        @jax.jit
        def step(params, opt_state, tokens):
            (loss, metrics), grads = jax.value_and_grad(
                mixtral.loss_fn, has_aux=True)(params, tokens, cfg,
                                               mesh=mesh)
            updates, opt_state = tx.update(grads, opt_state)
            return optax.apply_updates(params, updates), opt_state, metrics

        tokens = jnp.asarray(
            np.random.default_rng(0).integers(0, cfg.vocab_size, (4, 32)),
            jnp.int32)
        losses = []
        for _ in range(8):
            params, opt_state, metrics = step(params, opt_state, tokens)
            losses.append(float(metrics["loss"]))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0], losses


def test_mixtral_active_params_fraction():
    cfg = mixtral.MIXTRAL_8X7B
    total, active = cfg.param_count(), cfg.active_param_count()
    # 8x7B: ~47B total, ~13B active — the sparse-compute signature.
    assert total / active > 3.0
