"""uv / conda runtime-env plugins (reference analog:
python/ray/tests/test_runtime_env_uv.py, test_runtime_env_conda_and_pip.py
— the reference's conda tests stub the binary the same way, since CI
images don't ship it; this image ships neither uv nor conda, so both
tools are driven through RTPU_*_BIN stub executables that delegate to
venv/pip, exercising the real command construction, cache keying, and
atomic-publish paths)."""

import os
import stat
import sys

import pytest

import ray_tpu
from tests.test_runtime_env import _build_tiny_wheel

UV_STUB = """#!/bin/sh
# stub uv: "uv venv [--system-site-packages] --python PY DIR" and
# "uv pip install --python PY [args...]"
echo "$@" >> "$RTPU_UV_STUB_LOG"
cmd="$1"; shift
if [ "$cmd" = "venv" ]; then
    py=""; dir=""; flags=""
    while [ $# -gt 0 ]; do
        case "$1" in
            --system-site-packages) flags="--system-site-packages";;
            --python) py="$2"; shift;;
            *) dir="$1";;
        esac
        shift
    done
    exec "$py" -m venv $flags "$dir"
elif [ "$cmd" = "pip" ]; then
    sub="$1"; shift   # install
    py=""
    args=""
    while [ $# -gt 0 ]; do
        case "$1" in
            --python) py="$2"; shift;;
            *) args="$args $1";;
        esac
        shift
    done
    exec "$py" -m pip $sub --quiet --disable-pip-version-check $args
fi
exit 2
"""

CONDA_STUB = """#!/bin/sh
# stub conda: "conda run -n NAME python -c CODE" and
# "conda env create -p DIR -f FILE"
echo "$@" >> "$RTPU_CONDA_STUB_LOG"
if [ "$1" = "run" ]; then
    shift; shift; name="$1"; shift  # -n NAME
    exec "$@"
elif [ "$1" = "env" ] && [ "$2" = "create" ]; then
    dir="$4"; spec="$6"
    %PYTHON% -m venv "$dir" || exit 1
    cp "$spec" "$dir/conda-spec.json"
    exit 0
fi
exit 2
"""


@pytest.fixture(scope="module")
def stub_cluster(tmp_path_factory):
    """Cluster whose node processes inherit stub uv/conda binaries (env
    must be set BEFORE init so spawned nodes see it)."""
    base = tmp_path_factory.mktemp("stubs")
    uv = base / "uv"
    uv.write_text(UV_STUB)
    conda = base / "conda"
    conda.write_text(CONDA_STUB.replace("%PYTHON%", sys.executable))
    for p in (uv, conda):
        p.chmod(p.stat().st_mode | stat.S_IEXEC)
    old = {}
    env = {
        "RTPU_UV_BIN": str(uv),
        "RTPU_CONDA_BIN": str(conda),
        "RTPU_UV_STUB_LOG": str(base / "uv.log"),
        "RTPU_CONDA_STUB_LOG": str(base / "conda.log"),
        # Fresh cache per module: cached interpreters from other runs
        # would skip the code paths under test.
        "RTPU_RUNTIME_ENV_DIR": str(base / "envs"),
    }
    for k, v in env.items():
        old[k] = os.environ.get(k)
        os.environ[k] = v
    rt = ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield base
    ray_tpu.shutdown()
    for k, v in old.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


def test_uv_env_installs_and_isolates(stub_cluster, tmp_path):
    wheels = _build_tiny_wheel(tmp_path, name="rtpu_uvtest_pkg",
                               version="2.0.0")
    env = {"uv": {"packages": ["rtpu_uvtest_pkg"], "no_index": True,
                  "find_links": wheels}}

    @ray_tpu.remote(runtime_env=env)
    def with_pkg():
        import rtpu_uvtest_pkg

        return rtpu_uvtest_pkg.marker()

    @ray_tpu.remote
    def without_pkg():
        try:
            import rtpu_uvtest_pkg  # noqa: F401

            return "leaked"
        except ImportError:
            return "isolated"

    assert ray_tpu.get(with_pkg.remote(), timeout=180) == "installed-2.0.0"
    assert ray_tpu.get(without_pkg.remote(), timeout=60) == "isolated"
    log = (stub_cluster / "uv.log").read_text()
    assert "venv --system-site-packages" in log
    assert "pip install" in log and "--no-index" in log


def test_conda_dict_spec_creates_prefix_env(stub_cluster):
    from ray_tpu.core.runtime_env import (resolve_python_executable,
                                          validate_runtime_env)

    env = validate_runtime_env(
        {"conda": {"dependencies": ["python"], "name": "spec-env"}})
    python = resolve_python_executable(env)
    assert python and os.path.exists(python)
    # The spec file conda saw carries the dict.
    spec = os.path.join(os.path.dirname(os.path.dirname(python)),
                        "conda-spec.json")
    assert os.path.exists(spec)
    # Cache hit returns the same interpreter without re-creating.
    assert resolve_python_executable(env) == python


def test_conda_named_env_resolves_interpreter(stub_cluster):
    from ray_tpu.core.runtime_env import (resolve_python_executable,
                                          validate_runtime_env)

    env = validate_runtime_env({"conda": "prod-env"})
    # Stub `conda run` executes the command with the host python.
    assert resolve_python_executable(env) == sys.executable
    log = (stub_cluster / "conda.log").read_text()
    assert "run -n prod-env" in log


def test_interpreter_sources_mutually_exclusive():
    from ray_tpu.core.runtime_env import validate_runtime_env

    with pytest.raises(ValueError, match="mutually exclusive"):
        validate_runtime_env({"pip": ["x"], "uv": ["y"]})
    with pytest.raises(ValueError, match="mutually exclusive"):
        validate_runtime_env({"conda": "base", "py_executable": "/x"})


def test_missing_tool_raises(monkeypatch, tmp_path):
    from ray_tpu.core.runtime_env import (resolve_python_executable,
                                          validate_runtime_env)

    monkeypatch.delenv("RTPU_UV_BIN", raising=False)
    monkeypatch.setenv("PATH", str(tmp_path))  # nothing on PATH
    monkeypatch.setenv("RTPU_RUNTIME_ENV_DIR", str(tmp_path / "envs"))
    env = validate_runtime_env({"uv": ["somepkg"]})
    with pytest.raises(RuntimeError, match="uv executable"):
        resolve_python_executable(env)
