"""Remote-driver tier ("client mode") end-to-end tests.

Parity target: the reference's Ray Client test surface
(reference: python/ray/tests/test_client.py — tasks/actors/objects through
util/client/worker.py). The client runs in a subprocess that is NOT part of
the cluster (no node manager, no shm store): everything rides one framed-RPC
connection to the gateway started by the driver.
"""

import os
import subprocess
import sys
import textwrap

import pytest

import ray_tpu
from ray_tpu.client.server import start_gateway
from ray_tpu.core.runtime_context import require_runtime


CLIENT_SCRIPT = textwrap.dedent("""
    import sys
    import ray_tpu

    ray_tpu.init(address="client://" + sys.argv[1])

    # ---- tasks ----
    @ray_tpu.remote
    def add(a, b):
        return a + b

    assert ray_tpu.get(add.remote(2, 3)) == 5

    # pass-by-ref args + nested refs in results
    big = ray_tpu.put(list(range(1000)))
    @ray_tpu.remote
    def head3(xs):
        return xs[:3]
    assert ray_tpu.get(head3.remote(big)) == [0, 1, 2]

    @ray_tpu.remote
    def make_ref():
        return [ray_tpu.put("nested")]

    inner = ray_tpu.get(make_ref.remote())
    assert ray_tpu.get(inner[0]) == "nested"

    # multiple returns + wait
    @ray_tpu.remote(num_returns=2)
    def two():
        return 1, 2
    r1, r2 = two.remote()
    ready, pending = ray_tpu.wait([r1, r2], num_returns=2, timeout=30)
    assert len(ready) == 2 and not pending
    assert ray_tpu.get([r1, r2]) == [1, 2]

    # task errors propagate
    @ray_tpu.remote
    def boom():
        raise ValueError("kapow")
    try:
        ray_tpu.get(boom.remote())
    except Exception as e:
        assert "kapow" in str(e), e
    else:
        raise AssertionError("expected task error")

    # ---- actors ----
    @ray_tpu.remote
    class Counter:
        def __init__(self, start):
            self.n = start
        def incr(self, k=1):
            self.n += k
            return self.n

    c = Counter.remote(10)
    assert ray_tpu.get(c.incr.remote()) == 11
    assert ray_tpu.get(c.incr.remote(5)) == 16

    # named detached actor survives this client
    d = Counter.options(name="client-detached", lifetime="detached").remote(0)
    assert ray_tpu.get(d.incr.remote()) == 1

    # named lookup from the client
    again = ray_tpu.get_actor("client-detached")
    assert ray_tpu.get(again.incr.remote()) == 2

    # ---- cluster info / kv ----
    assert len(ray_tpu.nodes()) >= 1
    assert ray_tpu.cluster_resources().get("CPU", 0) >= 1

    from ray_tpu.core.runtime_context import require_runtime
    r = require_runtime()
    r.kv_put("client-key", b"v1")
    assert r.kv_get("client-key") == b"v1"
    assert "client-key" in r.kv_keys()

    ray_tpu.shutdown()
    print("CLIENT_OK")
""")


@pytest.fixture
def gateway(cluster_init):
    server = start_gateway(require_runtime())
    yield server.address
    server.stop()


def _run_client(address: str, script: str) -> subprocess.CompletedProcess:
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    return subprocess.run(
        [sys.executable, "-c", script, address],
        capture_output=True, text=True, timeout=180, env=env)


def test_client_mode_end_to_end(gateway):
    proc = _run_client(gateway, CLIENT_SCRIPT)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "CLIENT_OK" in proc.stdout

    # The detached actor must survive the client's exit...
    handle = ray_tpu.get_actor("client-detached")
    assert ray_tpu.get(handle.incr.remote()) == 3
    ray_tpu.kill(handle)


DISCONNECT_SCRIPT = textwrap.dedent("""
    import sys
    import ray_tpu

    ray_tpu.init(address="client://" + sys.argv[1])

    @ray_tpu.remote
    class Owned:
        def ping(self):
            return "pong"

    o = Owned.options(name="client-owned").remote()
    assert ray_tpu.get(o.ping.remote()) == "pong"
    # exit WITHOUT shutdown: the gateway session cleanup must kill the
    # session-owned (non-detached) actor.
    print("CLIENT_EXITING")
""")


def test_client_disconnect_kills_owned_actors(gateway):
    import time

    proc = _run_client(gateway, DISCONNECT_SCRIPT)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "CLIENT_EXITING" in proc.stdout

    # Session cleanup is asynchronous w.r.t. process exit.
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            aid = require_runtime().get_actor("client-owned")
        except ValueError:
            break  # name gone: killed
        # name may linger briefly while the kill propagates; check liveness
        alive = any(a.get("actor_id") == aid.hex() and
                    a.get("state") not in ("DEAD",)
                    for a in require_runtime().list_actors())
        if not alive:
            break
        time.sleep(0.5)
    else:
        pytest.fail("session-owned actor was not killed on disconnect")
