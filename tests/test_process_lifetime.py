"""Process-lifetime binding without preexec_fn (VERDICT weak #7: os.fork
warnings from fork-with-JAX-threads were a known deadlock class; reference
analog: raylet/worker death-signal plumbing)."""

import os
import signal
import subprocess
import sys
import textwrap
import time
import warnings


def test_no_fork_warnings_on_cluster_spawn():
    """Spawning head/node/workers must not take the raw-fork path (the
    JAX-multithreaded-fork RuntimeWarning class)."""
    code = textwrap.dedent("""
        import warnings, sys
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            import ray_tpu
            ray_tpu.init(num_cpus=1)

            @ray_tpu.remote
            def f():
                return 1

            assert ray_tpu.get(f.remote(), timeout=60) == 1
            ray_tpu.shutdown()
        bad = [x for x in w if "fork" in str(x.message)]
        sys.exit(1 if bad else 0)
    """)
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=180)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)


def test_sigkilled_driver_leaks_no_cluster():
    """PDEATHSIG is armed by the CHILD (bind_to_parent): a SIGKILL'd
    driver's head/node processes must still die."""
    driver = textwrap.dedent("""
        import sys, time
        import ray_tpu
        ray_tpu.init(num_cpus=1)
        from ray_tpu.core.runtime_context import require_runtime
        pids = [p.pid for p in require_runtime()._procs]
        print("PIDS " + " ".join(map(str, pids)), flush=True)
        time.sleep(120)
    """)
    p = subprocess.Popen([sys.executable, "-c", driver],
                         stdout=subprocess.PIPE, text=True)
    try:
        line = p.stdout.readline()
        assert line.startswith("PIDS"), line
        pids = [int(x) for x in line.split()[1:]]
        assert pids
    finally:
        os.kill(p.pid, signal.SIGKILL)
        p.wait()
    deadline = time.time() + 20
    alive = pids
    while time.time() < deadline:
        alive = [pid for pid in pids if os.path.exists(f"/proc/{pid}")]
        if not alive:
            break
        time.sleep(0.5)
    assert not alive, f"cluster processes leaked: {alive}"
