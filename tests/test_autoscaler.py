"""Autoscaler tests: unmet demand triggers scale-up; idle nodes reap
(reference analog: python/ray/autoscaler/v2 tests + fake node provider).
"""

import threading
import time

import pytest

import ray_tpu
from ray_tpu.autoscaler import Autoscaler, AutoscalerConfig, LocalNodeProvider


@pytest.fixture
def cluster():
    rt = ray_tpu.init(num_cpus=2)
    yield rt
    ray_tpu.shutdown()


def test_infeasible_demand_triggers_scale_up_then_idle_reap(cluster):
    provider = LocalNodeProvider(cluster, node_types={"cpu": {"CPU": 4.0}})
    scaler = Autoscaler(cluster, provider, AutoscalerConfig(
        max_nodes=4, idle_timeout_s=3.0, demand_window_s=20.0))

    @ray_tpu.remote(num_cpus=4)
    def big():
        time.sleep(1.0)
        return ray_tpu.get_runtime_context().node_id

    # Infeasible on the 2-CPU head node: the lease layer records unmet
    # demand at the head while the task stays queued.
    refs = [big.remote() for _ in range(2)]

    # The demand report rides the lease/spillback path asynchronously: on
    # a loaded host one fixed sleep raced it (suite-order flake). Poll the
    # scale-up decision instead of betting on a single instant.
    deadline = time.monotonic() + 30
    launched = []
    while time.monotonic() < deadline and not launched:
        time.sleep(1.0)
        launched = scaler.step()["launched"]
    assert launched, "no scale-up despite infeasible demand"
    # The queued tasks complete on the new capacity.
    nids = ray_tpu.get(refs, timeout=120)
    assert len(provider.non_terminated_nodes()) >= 1
    new_nodes = set(provider.non_terminated_nodes())
    assert set(nids) <= new_nodes, "tasks did not run on autoscaled nodes"

    # Idle reap: no demand; after idle_timeout the nodes drain + die.
    # Each launched node's idle timer starts when IT is first seen idle
    # (the one that ran tasks goes idle later), so reaps can land in
    # different steps — poll until the provider is empty, not until the
    # first reap.
    deadline = time.monotonic() + 60
    reaped = []
    while time.monotonic() < deadline and provider.non_terminated_nodes():
        time.sleep(1.0)
        reaped += scaler.step()["reaped"]
    assert reaped, "idle autoscaled node was never reaped"
    assert not provider.non_terminated_nodes()


class _FakeHead:
    """Head stub: serves a canned get_demand state, records drains."""

    def __init__(self, state):
        self.state = state
        self.drained = []

    def retrying_call(self, method, *args, timeout=None):
        if method == "get_demand":
            return self.state
        if method == "drain_node":
            # Like the real head: a drained node leaves the node table,
            # so later get_demand calls no longer list it.
            self.drained.append(args[0])
            self.state["nodes"] = [n for n in self.state["nodes"]
                                   if n["node_id"] != args[0]]
            return None
        raise AssertionError(method)


class _FakeRT:
    def __init__(self, state):
        self.head = _FakeHead(state)


class _MockProvider:
    """Provider stub: tracks nodes in a set; terminate can be failed."""

    node_types = {"cpu": {"CPU": 4.0}}

    def __init__(self, nodes, fail_terminate=False):
        self.nodes = set(nodes)
        self.fail_terminate = fail_terminate
        self.terminated = []

    def create_node(self, node_type):
        raise AssertionError("no scale-up expected")

    def terminate_node(self, pid):
        if self.fail_terminate:
            raise RuntimeError("cloud API error")
        self.nodes.discard(pid)
        self.terminated.append(pid)

    def non_terminated_nodes(self):
        return sorted(self.nodes)


def _idle_state(node_ids):
    return {
        "unmet": [],
        "nodes": [{"node_id": nid, "alive": True,
                   "resources": {"CPU": 4.0}, "available": {"CPU": 4.0},
                   "labels": {}} for nid in node_ids],
    }


def test_reap_terminates_via_provider_deterministic():
    """A reported reap implies the provider no longer lists the node
    (VERDICT r4: reap must terminate through the provider, then report)."""
    state = _idle_state(["n1"])
    rt = _FakeRT(state)
    provider = _MockProvider(["n1"])
    scaler = Autoscaler(rt, provider, AutoscalerConfig(
        max_nodes=4, min_nodes=0, idle_timeout_s=0.0))
    scaler._managed["n1"] = None

    did = scaler.step()
    assert did["reaped"] == ["n1"]
    assert provider.non_terminated_nodes() == []
    assert rt.head.drained == ["n1"]
    # Every pid ever reported reaped is gone from the provider.
    assert not (set(did["reaped"])
                & set(provider.non_terminated_nodes()))


def test_reap_not_reported_when_provider_terminate_fails():
    state = _idle_state(["n1"])
    rt = _FakeRT(state)
    provider = _MockProvider(["n1"], fail_terminate=True)
    scaler = Autoscaler(rt, provider, AutoscalerConfig(
        max_nodes=4, min_nodes=0, idle_timeout_s=0.0))
    scaler._managed["n1"] = None

    did = scaler.step()
    assert did["reaped"] == []
    assert provider.non_terminated_nodes() == ["n1"]
    # Node stays managed, so the reap retries on a later pass.
    assert "n1" in scaler._managed
    provider.fail_terminate = False
    did = scaler.step()
    assert did["reaped"] == ["n1"]
    assert provider.non_terminated_nodes() == []


def test_scale_up_respects_max_nodes(cluster):
    provider = LocalNodeProvider(cluster, node_types={"cpu": {"CPU": 4.0}})
    scaler = Autoscaler(cluster, provider,
                        AutoscalerConfig(max_nodes=2, max_launch_per_step=8))

    @ray_tpu.remote(num_cpus=4)
    def big():
        time.sleep(0.2)
        return 1

    refs = [big.remote() for _ in range(12)]
    time.sleep(1.0)
    scaler.step()
    time.sleep(1.0)
    scaler.step()
    # head node + at most (max_nodes - 1) autoscaled (head counts toward
    # the cluster total the scaler clamps against).
    assert len(provider.non_terminated_nodes()) <= 2
    ray_tpu.get(refs, timeout=180)


def test_bin_packing_absorbs_multiple_demands_per_node(cluster):
    provider = LocalNodeProvider(cluster, node_types={"cpu": {"CPU": 4.0}})
    scaler = Autoscaler(cluster, provider, AutoscalerConfig(max_nodes=8))

    @ray_tpu.remote(num_cpus=2)
    def mid():
        time.sleep(1.5)
        return 1

    # Head has 2 CPUs: one mid runs there; the others queue. 4 unmet
    # 2-CPU demands fit in ONE 4-CPU node x2, not four nodes.
    refs = [mid.remote() for _ in range(5)]
    time.sleep(2.5)  # one backlog report cycle
    did = scaler.step()
    # 5 x 2-CPU demands pack into <= 3 x 4-CPU nodes (NOT one node per
    # demand); the exact count depends on how many had already dispatched
    # when the backlog snapshot was taken.
    assert 1 <= len(did["launched"]) <= 3, did
    ray_tpu.get(refs, timeout=120)
