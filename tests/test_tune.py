"""Tune-lite: search spaces, concurrent trials, ASHA pruning (reference
test model: python/ray/tune/tests/test_tune_basics, test_trial_scheduler).
"""

import time

import pytest

import ray_tpu
import ray_tpu.tune as tune


@pytest.fixture(scope="module")
def cluster():
    rt = ray_tpu.init(num_cpus=8)
    yield rt
    ray_tpu.shutdown()


def test_grid_and_random_variants():
    from ray_tpu.tune.search import generate_variants

    space = {"lr": tune.grid_search([0.1, 0.01]),
             "wd": tune.grid_search([0, 1]),
             "h": tune.choice([32, 64]),
             "fixed": 7}
    vs = generate_variants(space, num_samples=2, seed=0)
    assert len(vs) == 2 * 2 * 2  # grid cross-product x samples
    assert all(v["fixed"] == 7 for v in vs)
    assert {(v["lr"], v["wd"]) for v in vs} == {(0.1, 0), (0.1, 1),
                                               (0.01, 0), (0.01, 1)}


def test_tuner_finds_best(cluster):
    def objective(config):
        # Quadratic bowl: best at x=3.
        score = -(config["x"] - 3) ** 2
        tune.report({"score": score, "x": config["x"]})

    tuner = tune.Tuner(
        objective,
        param_space={"x": tune.grid_search([0, 1, 2, 3, 4, 5])},
        tune_config=tune.TuneConfig(metric="score", mode="max",
                                    max_concurrent_trials=3),
    )
    grid = tuner.fit()
    assert len(grid) == 6
    best = grid.get_best_result()
    assert best.config["x"] == 3
    assert best.metrics["score"] == 0


def test_return_style_trainable(cluster):
    def objective(config):
        return {"loss": config["x"] * 2}

    grid = tune.Tuner(
        objective, param_space={"x": tune.grid_search([1, 2])},
        tune_config=tune.TuneConfig(metric="loss", mode="min"),
    ).fit()
    assert grid.get_best_result().metrics["loss"] == 2


def test_trial_error_is_captured(cluster):
    def objective(config):
        if config["x"] == 1:
            raise RuntimeError("bad trial")
        tune.report({"ok": 1})

    grid = tune.Tuner(
        objective, param_space={"x": tune.grid_search([0, 1])},
        tune_config=tune.TuneConfig(metric="ok", mode="max"),
    ).fit()
    assert len(grid.errors) == 1
    assert "bad trial" in grid.errors[0].error
    assert grid.get_best_result().metrics["ok"] == 1


def test_asha_prunes_bad_trials(cluster):
    def objective(config):
        for step in range(12):
            tune.report({"acc": config["quality"] * (step + 1)})

    sched = tune.ASHAScheduler(metric="acc", mode="max", grace_period=2,
                               reduction_factor=2, max_t=12)
    grid = tune.Tuner(
        objective,
        param_space={"quality": tune.grid_search([0.1, 0.2, 0.9, 1.0])},
        tune_config=tune.TuneConfig(metric="acc", mode="max",
                                    scheduler=sched,
                                    max_concurrent_trials=4),
    ).fit()
    best = grid.get_best_result()
    assert best.config["quality"] == 1.0
    # Successive halving: the weak half dies at the FIRST rung, the
    # runner-up at a later rung, only the winner runs to max_t.
    iters = {r.config["quality"]: len(r.history) for r in grid}
    assert iters[1.0] == 12
    assert iters[0.1] < iters[1.0] and iters[0.2] < iters[1.0]
    assert iters[0.1] <= iters[0.9] and iters[0.2] <= iters[0.9]
    pruned = [r for r in grid
              if r.stopped_early and len(r.history) < len(best.history)]
    assert len(pruned) >= 2
