"""Paged decode-attention kernel vs references (interpret mode on CPU —
the decode_attention.py test idiom): the block-table read must be
bit-equal to the contiguous read for identity tables, exact against the
gather reference for scattered tables, and the llama/engine dispatch
glue must reproduce the unpaged model path."""

import dataclasses

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from ray_tpu.ops.decode_attention import decode_attention_reference  # noqa: E402
from ray_tpu.ops.paged_decode import (paged_decode_attention,  # noqa: E402
                                      paged_decode_attention_reference)


def _inputs(b=2, h=8, kh=4, s=64, d=16, seed=0, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(seed), 4)
    q = jax.random.normal(ks[0], (b, h, d), dtype)
    k = jax.random.normal(ks[1], (b, kh, s, d), dtype)
    v = jax.random.normal(ks[2], (b, kh, s, d), dtype)
    lengths = jnp.asarray(
        jax.random.randint(ks[3], (b,), 1, s + 1), jnp.int32)
    return q, k, v, lengths


def _identity_table(b, s, page):
    np_row = s // page
    return jnp.arange(b * np_row, dtype=jnp.int32).reshape(b, np_row)


def _scatter_pages(k, v, page, seed=0):
    """Shuffle every (seq, page) into a random physical page of an
    equally-sized pool; returns (pool_k, pool_v, table)."""
    b, kh, s, d = k.shape
    np_row = s // page
    rng = np.random.default_rng(seed)
    perm = rng.permutation(b * np_row)
    kp = np.asarray(k).reshape(b, kh, np_row, page, d)
    vp = np.asarray(v).reshape(b, kh, np_row, page, d)
    pool_k = np.zeros_like(kp)
    pool_v = np.zeros_like(vp)
    table = np.zeros((b, np_row), np.int32)
    for bi in range(b):
        for pi in range(np_row):
            t = int(perm[bi * np_row + pi])
            table[bi, pi] = t
            pool_k[t // np_row, :, t % np_row] = kp[bi, :, pi]
            pool_v[t // np_row, :, t % np_row] = vp[bi, :, pi]
    return (jnp.asarray(pool_k.reshape(b, kh, s, d)),
            jnp.asarray(pool_v.reshape(b, kh, s, d)),
            jnp.asarray(table))


def test_identity_table_bit_equal_to_contiguous_reference():
    """A slot-identity table (the engine's table) reads the exact same
    rows in the exact same order — the paged reference must be
    BIT-equal to the contiguous decode reference on live rows."""
    q, k, v, lengths = _inputs()
    table = _identity_table(2, 64, 8)
    ref = decode_attention_reference(
        q, k.transpose(0, 2, 1, 3), v.transpose(0, 2, 1, 3), lengths)
    got = paged_decode_attention_reference(q, k, v, table, lengths, 8)
    assert jnp.array_equal(ref, got)


@pytest.mark.parametrize("shape", [
    dict(b=2, h=8, kh=4, s=64, d=16),     # GQA
    dict(b=1, h=4, kh=4, s=96, d=32),     # MHA, 12 pages
    dict(b=3, h=16, kh=2, s=64, d=16),    # deep GQA groups
])
def test_kernel_matches_reference_identity(shape):
    q, k, v, lengths = _inputs(**shape)
    page = 8
    table = _identity_table(shape["b"], shape["s"], page)
    expect = paged_decode_attention_reference(q, k, v, table, lengths,
                                              page)
    got = paged_decode_attention(q, k, v, table, lengths,
                                 page_size=page, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                               rtol=2e-5, atol=2e-5)


def test_kernel_scattered_table_reads_in_place():
    """Pages scattered across the pool: the kernel must follow the
    table (no contiguity assumption) and still match the un-scattered
    contiguous computation exactly."""
    q, k, v, lengths = _inputs(b=2, h=8, kh=4, s=64, d=16)
    page = 8
    pool_k, pool_v, table = _scatter_pages(k, v, page)
    ref = decode_attention_reference(
        q, k.transpose(0, 2, 1, 3), v.transpose(0, 2, 1, 3), lengths)
    got_ref = paged_decode_attention_reference(q, pool_k, pool_v, table,
                                               lengths, page)
    assert jnp.array_equal(ref, got_ref)  # gather undoes the scatter
    got = paged_decode_attention(q, pool_k, pool_v, table, lengths,
                                 page_size=page, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_pages_past_length_never_contribute():
    """Poison every row at or past each sequence's length — including
    WHOLE pages the index map never streams — and check invariance."""
    q, k, v, _ = _inputs(b=2, h=4, kh=4, s=64, d=16)
    page = 8
    lengths = jnp.asarray([3, 41], jnp.int32)  # partial first/last pages
    table = _identity_table(2, 64, page)
    expect = paged_decode_attention_reference(q, k, v, table, lengths,
                                              page)
    k_p = k.at[0, :, 3:].set(100.0).at[1, :, 41:].set(100.0)
    v_p = v.at[0, :, 3:].set(-77.0).at[1, :, 41:].set(-77.0)
    got = paged_decode_attention(q, k_p, v_p, table, lengths,
                                 page_size=page, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                               rtol=2e-5, atol=2e-5)


def test_zero_length_slot_attends_nothing():
    """A freed/empty slot (length 0) outputs ~0 — never the mean of
    whatever physical page the parked index map landed on."""
    q, k, v, _ = _inputs(b=2, h=4, kh=4, s=64, d=16)
    lengths = jnp.asarray([0, 64], jnp.int32)
    table = _identity_table(2, 64, 8)
    got = paged_decode_attention(q, k, v, table, lengths,
                                 page_size=8, interpret=True)
    np.testing.assert_allclose(np.asarray(got)[0], 0.0, atol=1e-6)
    expect = paged_decode_attention_reference(q, k, v, table, lengths, 8)
    np.testing.assert_allclose(np.asarray(got)[1],
                               np.asarray(expect)[1],
                               rtol=2e-5, atol=2e-5)


def test_non_multiple_cache_rows_rejected():
    q, k, v, lengths = _inputs(b=1, h=4, kh=4, s=60, d=16)
    table = jnp.zeros((1, 4), jnp.int32)
    with pytest.raises(ValueError, match="multiple"):
        paged_decode_attention(q, k, v, table, lengths, page_size=8)


def test_bfloat16_inputs():
    q, k, v, lengths = _inputs(b=1, h=4, kh=2, s=64, d=16,
                               dtype=jnp.bfloat16)
    table = _identity_table(1, 64, 8)
    expect = paged_decode_attention_reference(q, k, v, table, lengths, 8)
    got = paged_decode_attention(q, k, v, table, lengths,
                                 page_size=8, interpret=True)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(expect, np.float32),
                               rtol=2e-2, atol=2e-2)


def test_llama_paged_dispatch_glue():
    """The MODEL-side integration (llama._block's identity table /
    lengths / page-size plumbing) against the unpaged path — both the
    gather-reference dispatch (paged_decode=True off-TPU) and the
    interpret-mode kernel."""
    from ray_tpu.models import llama

    base = llama.tiny_config(max_seq_len=64)
    cfg_r = dataclasses.replace(base, paged_decode=True, decode_page=8)
    cfg_i = dataclasses.replace(base, paged_decode="interpret",
                                decode_page=8)
    cfg_x = dataclasses.replace(base, use_decode_kernel=False)
    params = llama.init_params(base, jax.random.PRNGKey(0))
    caches = {n: llama.init_kv_cache(base, 2, 64) for n in "rix"}
    cfgs = {"r": cfg_r, "i": cfg_i, "x": cfg_x}
    prompt = jnp.asarray([[5, 9, 3, 7], [2, 8, 1, 4]], jnp.int32)
    outs = {}
    for n in "rix":  # prefill is the same unpaged path everywhere
        outs[n], caches[n] = llama.forward_with_cache(
            params, prompt, caches[n], 0, cfgs[n])
    np.testing.assert_allclose(np.asarray(outs["r"]),
                               np.asarray(outs["x"]), rtol=2e-4,
                               atol=2e-4)
    tok = jnp.argmax(outs["x"][:, -1], -1)[:, None].astype(jnp.int32)
    for step in range(3):
        for n in "rix":
            outs[n], caches[n] = llama.forward_with_cache(
                params, tok, caches[n], 4 + step, cfgs[n])
        for n in "ri":
            np.testing.assert_allclose(
                np.asarray(outs[n]), np.asarray(outs["x"]),
                rtol=2e-3, atol=2e-3)
        tok = jnp.argmax(outs["x"][:, -1], -1)[:, None].astype(jnp.int32)
