"""rtpu-lint + runtime lock witness.

One positive and one negative fixture per static rule, the baseline
mechanics, and the RTPU_DEBUG_LOCKS witness: deliberate lock-order
deadlock detected online, Condition integration, reentrancy, hold-time
reporting, and the no-false-positive cases (consistent order,
same-name sibling instances).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

import pytest

from ray_tpu.devtools import lock_debug
from ray_tpu.devtools.lint import (DEFAULT_BASELINE, lint_source,
                                   load_baseline, new_findings,
                                   write_baseline)

NM = "ray_tpu.cluster.node_manager"
WM = "ray_tpu.cluster.worker_main"
PROTO = "ray_tpu.cluster.protocol"


def rules(findings):
    return sorted({f.rule for f in findings})


# ------------------------------------------- retry-without-deadline


def test_unbounded_retrying_call_loop_flagged():
    src = (
        "import time\n"
        "def f(self):\n"
        "    while True:\n"
        "        try:\n"
        "            return self.head.retrying_call('ping', timeout=5)\n"
        "        except Exception as e:\n"
        "            print(e)\n"
        "            time.sleep(0.1)\n"
    )
    fs = lint_source(src, NM, "x.py")
    assert rules(fs) == ["retry-without-deadline"]


def test_unbounded_socket_connect_loop_flagged():
    src = (
        "import socket, time\n"
        "def f(self):\n"
        "    while True:\n"
        "        try:\n"
        "            self.sock.connect(('h', 1))\n"
        "            return\n"
        "        except OSError as e:\n"
        "            print(e)\n"
        "            time.sleep(0.1)\n"
    )
    fs = lint_source(src, NM, "x.py")
    assert rules(fs) == ["retry-without-deadline"]


def test_deadline_bounded_retry_loop_clean():
    src = (
        "import time\n"
        "def f(self):\n"
        "    deadline = time.monotonic() + 30\n"
        "    while True:\n"
        "        try:\n"
        "            return self.head.retrying_call('ping', timeout=5)\n"
        "        except Exception as e:\n"
        "            print(e)\n"
        "            if time.monotonic() > deadline:\n"
        "                raise\n"
    )
    assert lint_source(src, NM, "x.py") == []


def test_attempt_counted_and_stop_event_loops_clean():
    counted = (
        "def f(self):\n"
        "    attempts = 0\n"
        "    while True:\n"
        "        try:\n"
        "            return self.head.retrying_call('ping')\n"
        "        except Exception as e:\n"
        "            print(e)\n"
        "            attempts += 1\n"
        "            if attempts > 5:\n"
        "                raise\n"
    )
    assert lint_source(counted, NM, "x.py") == []
    # Daemon loops that exit on the stop event are bounded by shutdown.
    daemon = (
        "def f(self):\n"
        "    while True:\n"
        "        if self._stop.is_set():\n"
        "            return\n"
        "        try:\n"
        "            self.head.retrying_call('register_node')\n"
        "        except Exception as e:\n"
        "            print(e)\n"
    )
    assert lint_source(daemon, NM, "x.py") == []


def test_success_break_alone_does_not_bound_retry_loop():
    # break on success is the NORMAL exit — the hang case is the one
    # where success never comes; break must not count as a bound.
    src = (
        "def f(self):\n"
        "    while True:\n"
        "        try:\n"
        "            self.head.retrying_call('ping')\n"
        "            break\n"
        "        except Exception as e:\n"
        "            print(e)\n"
    )
    assert rules(lint_source(src, NM, "x.py")) == ["retry-without-deadline"]


def test_retry_rule_ignores_nonretry_while_true_and_nested_defs():
    plain = (
        "def f(self):\n"
        "    while True:\n"
        "        self.queue.append(1)\n"
    )
    assert lint_source(plain, NM, "x.py") == []
    # A retry loop INSIDE a nested def belongs to that def's own visit;
    # the outer while must not inherit its calls.
    nested = (
        "def f(self):\n"
        "    while True:\n"
        "        if self._stop.is_set():\n"
        "            return\n"
        "        def cb():\n"
        "            return self.head.retrying_call('ping')\n"
        "        self.cbs.append(cb)\n"
    )
    assert lint_source(nested, NM, "x.py") == []


def test_retry_rule_suppressable_inline():
    src = (
        "def f(self):\n"
        "    while True:  # rtpu-lint: disable=retry-without-deadline\n"
        "        try:\n"
        "            return self.head.retrying_call('ping')\n"
        "        except Exception as e:\n"
        "            print(e)\n"
    )
    assert lint_source(src, NM, "x.py") == []


# ------------------------------------------------------------ lock-order


def test_lock_order_violation_flagged():
    src = (
        "def f(self):\n"
        "    with self._zygote_lock:\n"
        "        with self._zygote_io_lock:\n"
        "            pass\n")
    fs = lint_source(src, NM, "x.py")
    assert rules(fs) == ["lock-order"]
    assert "_zygote_io_lock" in fs[0].message


def test_lock_order_correct_nesting_clean():
    src = (
        "def f(self):\n"
        "    with self._zygote_io_lock:\n"
        "        with self._zygote_lock:\n"
        "            pass\n")
    assert lint_source(src, NM, "x.py") == []


def test_never_nested_group_flagged_either_order():
    for a, b in (("_seen_lock", "_done_lock"),
                 ("_done_lock", "_seen_lock")):
        src = (
            f"def f(self):\n"
            f"    with self.{a}:\n"
            f"        with self.{b}:\n"
            f"            pass\n")
        fs = lint_source(src, WM, "x.py")
        assert rules(fs) == ["lock-order"], (a, b)
        assert "never-nested" in fs[0].message


def test_acquire_call_under_with_checked():
    src = (
        "def f(self):\n"
        "    with self._zygote_lock:\n"
        "        self._zygote_io_lock.acquire()\n")
    assert rules(lint_source(src, NM, "x.py")) == ["lock-order"]


def test_other_module_pairs_not_declared_clean():
    src = (
        "def f(self):\n"
        "    with self._zygote_lock:\n"
        "        with self._zygote_io_lock:\n"
        "            pass\n")
    assert lint_source(src, "ray_tpu.other", "x.py") == []


# ---------------------------------------------------- blocking-under-lock


def test_blocking_calls_under_lock_flagged():
    src = (
        "import time, subprocess\n"
        "def f(self):\n"
        "    with self._lock:\n"
        "        time.sleep(1.0)\n"
        "        self.sock.recv(4)\n"
        "        subprocess.run(['true'])\n")
    fs = lint_source(src, NM, "x.py")
    assert [f.rule for f in fs] == ["blocking-under-lock"] * 3


def test_short_sleep_and_unlocked_io_clean():
    src = (
        "import time\n"
        "def f(self):\n"
        "    with self._lock:\n"
        "        time.sleep(0.001)\n"
        "    self.sock.recv(4)\n"
        "    time.sleep(5)\n")
    assert lint_source(src, NM, "x.py") == []


def test_io_serialization_locks_exempt():
    # _zygote_io_lock (node_manager) and send_lock (protocol) exist to
    # serialize blocking I/O: holding them across it is the point.
    src = (
        "def f(self):\n"
        "    with self._zygote_io_lock:\n"
        "        self.z.stdout.readline()\n")
    assert lint_source(src, NM, "x.py") == []
    src = (
        "def g(sock, lock):\n"
        "    with send_lock:\n"
        "        sock.sendmsg([b'x'])\n")
    assert lint_source(src, PROTO, "x.py") == []


def test_closure_defined_under_lock_not_flagged():
    # The closure's body runs LATER on another thread — it is lexically
    # inside the with-block but never executes under the lock.
    src = (
        "def f(self):\n"
        "    with self._lock:\n"
        "        def report():\n"
        "            self._head.retrying_call('x')\n"
        "        spawn(report)\n")
    assert lint_source(src, NM, "x.py") == []


def test_malformed_empty_suppression_comment_does_not_crash():
    src = (
        "def f():\n"
        "    try:\n"
        "        g()\n"
        "    except Exception:  # rtpu-lint: disable=\n"
        "        pass\n")
    # Empty rule list suppresses nothing — and must not IndexError.
    assert rules(lint_source(src, "m", "m.py")) == ["swallowed-exception"]


# -------------------------------------------------- close-without-shutdown


def test_close_without_shutdown_flagged():
    src = (
        "def f(self):\n"
        "    self._sock.close()\n")
    fs = lint_source(src, PROTO, "x.py")
    assert rules(fs) == ["close-without-shutdown"]


def test_shutdown_before_close_clean():
    src = (
        "def f(self):\n"
        "    self._sock.shutdown(2)\n"
        "    self._sock.close()\n"
        "def g(self):\n"
        "    _shutdown_socket(self._sock)\n")
    assert lint_source(src, PROTO, "x.py") == []


def test_close_in_nested_def_reported_once():
    src = (
        "def outer(self):\n"
        "    def inner():\n"
        "        self._sock.close()\n"
        "    return inner\n")
    fs = lint_source(src, PROTO, "x.py")
    assert len(fs) == 1 and fs[0].scope == "outer.inner"


def test_close_rule_scoped_to_socket_modules():
    src = (
        "def f(self):\n"
        "    self._sock.close()\n")
    assert lint_source(src, "ray_tpu.util.queue", "x.py") == []


# ------------------------------------------------------------- banned-api


def test_banned_set_mesh_and_shard_map():
    src = (
        "import jax\n"
        "from jax.experimental.shard_map import shard_map\n"
        "def f(m):\n"
        "    jax.sharding.set_mesh(m)\n")
    fs = lint_source(src, "ray_tpu.parallel.spmd", "x.py")
    assert [f.rule for f in fs] == ["banned-api"] * 2
    msgs = " ".join(f.message for f in fs)
    assert "mesh_context" in msgs and "compat shim" in msgs


def test_shard_map_import_allowed_in_compat_shim():
    src = "from jax.experimental.shard_map import shard_map\n"
    assert lint_source(src, "ray_tpu.ops.ring_attention", "x.py") == []


def test_inner_html_flagged_in_dashboard_strings_only():
    src = 'PAGE = "<script>el.innerHTML = x;</script>"\n'
    fs = lint_source(src, "ray_tpu.util.dashboard", "d.py")
    assert rules(fs) == ["banned-api"]
    assert lint_source(src, "ray_tpu.util.queue", "d.py") == []


def test_text_content_clean_in_dashboard():
    src = 'PAGE = "<script>el.textContent = x;</script>"\n'
    assert lint_source(src, "ray_tpu.util.dashboard", "d.py") == []


# ---------------------------------------------------- swallowed-exception


def test_silent_broad_except_flagged():
    src = (
        "def f():\n"
        "    try:\n"
        "        g()\n"
        "    except Exception:\n"
        "        pass\n")
    assert rules(lint_source(src, "m", "m.py")) == ["swallowed-exception"]


def test_logged_raised_or_used_excepts_clean():
    src = (
        "def f():\n"
        "    try:\n"
        "        g()\n"
        "    except Exception as e:\n"
        "        logger.debug('boom: %r', e)\n"
        "    try:\n"
        "        g()\n"
        "    except Exception:\n"
        "        raise\n"
        "    try:\n"
        "        g()\n"
        "    except Exception as e:\n"
        "        record(e)\n")
    assert lint_source(src, "m", "m.py") == []


def test_suppression_comments_honored():
    src = (
        "def f():\n"
        "    try:\n"
        "        g()\n"
        "    except Exception:  # rtpu-lint: disable=swallowed-exception\n"
        "        pass\n"
        "    try:\n"
        "        g()\n"
        "    except Exception:  # noqa: BLE001 — audited best-effort\n"
        "        pass\n")
    assert lint_source(src, "m", "m.py") == []


# --------------------------------------------------------- daemon-no-join


def test_daemon_thread_without_join_flagged():
    src = (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._t = threading.Thread(target=x, daemon=True)\n"
        "        self._t.start()\n")
    assert rules(lint_source(src, "m", "m.py")) == ["daemon-no-join"]


def test_daemon_thread_with_join_clean():
    src = (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._t = threading.Thread(target=x, daemon=True)\n"
        "    def close(self):\n"
        "        self._t.join(timeout=2)\n")
    assert lint_source(src, "m", "m.py") == []


# ---------------------------------------------------- span-not-closed


def test_span_call_without_with_flagged():
    src = (
        "from ray_tpu.util import tracing\n"
        "def f(name):\n"
        "    tracing.trace('run')\n"            # never closed
        "    h = tracing.span('child')\n"       # assigned, never with-ed
        "    return h\n")
    fs = lint_source(src, "m", "m.py")
    assert rules(fs) == ["span-not-closed"]
    assert len(fs) == 2


def test_span_as_context_manager_clean():
    src = (
        "from ray_tpu.util import tracing\n"
        "import contextlib\n"
        "def f(spec, name):\n"
        "    with tracing.trace('run') as t:\n"
        "        with tracing.span('child'):\n"
        "            pass\n"
        "    cm = tracing.remote_span('task', spec)\n"
        "    with cm as h:\n"                    # assigned-then-with
        "        pass\n"
        "    with contextlib.ExitStack() as stack:\n"
        "        stack.enter_context(tracing.span('s'))\n"
        "    return t\n")
    assert lint_source(src, "m", "m.py") == []


def test_bare_remote_span_and_alias_receiver_flagged():
    src = (
        "from ray_tpu.util import tracing as _tracing\n"
        "from ray_tpu.util.tracing import remote_span\n"
        "def f(spec):\n"
        "    remote_span('task', spec)\n"        # bare-name constructor
        "    _tracing.remote_span('task2', spec)\n")
    fs = lint_source(src, "m", "m.py")
    assert rules(fs) == ["span-not-closed"]
    assert len(fs) == 2


def test_span_rule_ignores_other_receivers_and_emit_api():
    src = (
        "def f(tracer, tracing):\n"
        "    tracer.span('not the module')\n"    # receiver not tracing-like
        "    tracing.emit_span('a', 0, 1)\n"     # manual API: no CM needed
        "    tracing.start_span('b')\n"
        "    tracing.current()\n")
    assert lint_source(src, "m", "m.py") == []


def test_span_rule_nested_def_has_own_scope():
    # The with lives in a NESTED def: the outer call is still unclosed.
    src = (
        "from ray_tpu.util import tracing\n"
        "def outer():\n"
        "    tracing.span('leak')\n"
        "    def inner():\n"
        "        with tracing.span('fine'):\n"
        "            pass\n"
        "    return inner\n")
    fs = lint_source(src, "m", "m.py")
    assert rules(fs) == ["span-not-closed"]
    assert len(fs) == 1


def test_span_rule_suppressable_inline():
    src = (
        "from ray_tpu.util import tracing\n"
        "def f():\n"
        "    tracing.span('x')  # rtpu-lint: disable=span-not-closed\n")
    assert lint_source(src, "m", "m.py") == []


# --------------------------------------------------------------- baseline


def test_baseline_tracks_legacy_and_fails_new(tmp_path):
    legacy = (
        "def f():\n"
        "    try:\n"
        "        g()\n"
        "    except Exception:\n"
        "        pass\n")
    findings = lint_source(legacy, "m", "m.py")
    bpath = str(tmp_path / "base.json")
    write_baseline(bpath, findings)
    baseline = load_baseline(bpath)
    assert new_findings(findings, baseline) == []
    # A SECOND swallow in the same scope exceeds the baselined count.
    grown = lint_source(legacy + (
        "    try:\n"
        "        g()\n"
        "    except Exception:\n"
        "        pass\n"), "m", "m.py")
    assert len(new_findings(grown, baseline)) == 1


def test_baseline_survives_line_drift(tmp_path):
    legacy = (
        "def f():\n"
        "    try:\n"
        "        g()\n"
        "    except Exception:\n"
        "        pass\n")
    bpath = str(tmp_path / "base.json")
    write_baseline(bpath, lint_source(legacy, "m", "m.py"))
    shifted = "import os\nX = 1\n\n\n" + legacy
    assert new_findings(lint_source(shifted, "m", "m.py"),
                        load_baseline(bpath)) == []


def test_cli_end_to_end(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("def f():\n"
                   "    try:\n"
                   "        g()\n"
                   "    except Exception:\n"
                   "        pass\n")
    bpath = tmp_path / "base.json"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, PYTHONPATH=repo)
    cmd = [sys.executable, "-m", "ray_tpu.devtools.lint", str(bad),
           "--baseline", str(bpath)]
    r = subprocess.run(cmd, env=env, cwd=repo, capture_output=True,
                       text=True)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "swallowed-exception" in r.stdout
    r = subprocess.run(cmd + ["--write-baseline"], env=env, cwd=repo,
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr
    data = json.loads(bpath.read_text())
    assert data["version"] == 2
    assert data["families"]["concurrency"]["findings"]
    r = subprocess.run(cmd, env=env, cwd=repo, capture_output=True,
                       text=True)
    assert r.returncode == 0, r.stdout + r.stderr


def test_write_baseline_refuses_partial_scan_of_packaged_baseline(
        tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("x = 1\n")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, PYTHONPATH=repo)
    before = open(DEFAULT_BASELINE, "rb").read()
    r = subprocess.run(
        [sys.executable, "-m", "ray_tpu.devtools.lint", str(bad),
         "--write-baseline"],
        env=env, cwd=repo, capture_output=True, text=True)
    assert r.returncode == 2, r.stdout + r.stderr
    assert "refusing" in r.stderr
    assert open(DEFAULT_BASELINE, "rb").read() == before


# --------------------------------------------------------- lock witness


@pytest.fixture
def debug_locks(monkeypatch):
    monkeypatch.setenv("RTPU_DEBUG_LOCKS", "1")
    lock_debug.reset()
    yield
    lock_debug.reset()


def test_make_lock_plain_when_disabled(monkeypatch):
    monkeypatch.delenv("RTPU_DEBUG_LOCKS", raising=False)
    lk = lock_debug.make_lock("x")
    assert not isinstance(lk, lock_debug.DebugLock)


def test_witness_reports_deliberate_deadlock(debug_locks):
    """Two threads acquire A/B in opposite orders and genuinely contend
    (held-while-wanting on both sides). The witness must report the
    cycle ONLINE even though neither inner acquire ever succeeds —
    edges are recorded on the attempt, lockdep-style."""
    A = lock_debug.make_lock("dl.A")
    B = lock_debug.make_lock("dl.B")
    barrier = threading.Barrier(2, timeout=5)

    def t1():
        with A:
            barrier.wait()
            if B.acquire(timeout=1.0):
                B.release()

    def t2():
        with B:
            barrier.wait()
            if A.acquire(timeout=1.0):
                A.release()

    threads = [threading.Thread(target=t1), threading.Thread(target=t2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(10)
    cycles = lock_debug.get_report()["cycles"]
    assert cycles, "deadlock cycle not reported"
    assert {"dl.A", "dl.B"} <= set(cycles[0]["chain"])


def test_consistent_order_no_cycle(debug_locks):
    A = lock_debug.make_lock("ok.A")
    B = lock_debug.make_lock("ok.B")
    for _ in range(3):
        with A:
            with B:
                pass
    assert lock_debug.get_report()["cycles"] == []
    assert lock_debug.get_report()["edges"].get("ok.A") == ["ok.B"]


def test_same_name_sibling_instances_no_self_cycle(debug_locks):
    # Two connections' send locks share a NAME; nesting two instances
    # is not an ordering fact and must not report a self-cycle.
    L1 = lock_debug.make_lock("conn.send_lock")
    L2 = lock_debug.make_lock("conn.send_lock")
    with L1:
        with L2:
            pass
    assert lock_debug.get_report()["cycles"] == []


def test_self_deadlock_probes_not_reported(debug_locks):
    # Timeout/non-blocking re-acquire probes and RLock re-entry are NOT
    # self-deadlocks and must stay silent.
    L = lock_debug.make_lock("self.L")
    with L:
        assert not L.acquire(timeout=0.05)
        L.acquire(blocking=False)
    rl = lock_debug.make_rlock("self.RL")
    with rl:
        with rl:
            pass
    assert lock_debug.get_report()["cycles"] == []


def test_blocking_self_deadlock_reported_pre_block(debug_locks):
    # A genuine blocking re-acquire of a non-reentrant lock can never
    # succeed: the witness must report it BEFORE parking the thread.
    L = lock_debug.make_lock("selfdl.L")
    done = []

    def victim():
        L.acquire()
        L.acquire()  # reported pre-block, then parks
        done.append(1)

    t = threading.Thread(target=victim, daemon=True)
    t.start()
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and \
            not lock_debug.get_report()["cycles"]:
        time.sleep(0.01)
    cycles = lock_debug.get_report()["cycles"]
    assert cycles and cycles[0]["chain"] == ["selfdl.L", "selfdl.L"]
    assert "self-deadlock" in cycles[0]["message"]
    # Unpark the victim (threading.Lock may be released by any thread)
    # so the test leaves no thread blocked forever.
    L._inner.release()
    t.join(5)
    assert done == [1]


def test_condition_integration_and_wait_clears_hold(debug_locks):
    lk = lock_debug.make_rlock("cv.L")
    cv = threading.Condition(lk)
    got = []

    def waiter():
        with cv:
            cv.wait(5)
            got.append(1)

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.2)
    with cv:
        cv.notify_all()
    t.join(5)
    assert got == [1]
    assert lock_debug.get_report()["cycles"] == []


def test_hold_time_reported(debug_locks, monkeypatch):
    monkeypatch.setenv("RTPU_DEBUG_LOCKS_HOLD_S", "0.05")
    L = lock_debug.make_lock("hold.L")
    with L:
        time.sleep(0.1)
    holds = lock_debug.get_report()["long_holds"]
    assert holds and holds[0]["lock"] == "hold.L"
    assert holds[0]["seconds"] >= 0.05
    from ray_tpu.util import metrics as _metrics

    m = _metrics.get_metric("rtpu_debug_lock_hold_exceeded")
    assert m is not None
    assert any(lbl.get("lock") == "hold.L" and v >= 1
               for lbl, v in m.items())


def test_repo_baseline_file_checked_in():
    assert os.path.exists(DEFAULT_BASELINE)
    data = json.load(open(DEFAULT_BASELINE))
    assert data["version"] == 2
    fams = data["families"]
    # Every rule family has a section with a schema version; the
    # concurrency section carries the legacy debt, the jax, dist, res,
    # and chan sections start (and should stay) empty — their findings
    # are fixed or allow-commented, not baselined.
    assert set(fams) == {"concurrency", "jax", "dist", "res", "chan"}
    for sec in fams.values():
        assert isinstance(sec["schema"], int)
    assert fams["concurrency"]["findings"]
