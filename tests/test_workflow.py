"""Workflow tests: durable DAG execution + exactly-once resume
(reference analog: python/ray/workflow/tests/test_basic_workflows.py).
"""

import os

import pytest

import ray_tpu
from ray_tpu import workflow


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    os.environ["RTPU_WORKFLOW_STORAGE"] = str(
        tmp_path_factory.mktemp("workflows"))
    rt = ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield rt
    ray_tpu.shutdown()
    os.environ.pop("RTPU_WORKFLOW_STORAGE", None)


def test_dag_executes_and_checkpoints(cluster, tmp_path):
    marker = tmp_path / "count.txt"

    @workflow.step
    def load(x):
        return x * 2

    @workflow.step
    def combine(a, b):
        with open(marker, "a") as f:
            f.write("ran\n")
        return a + b

    dag = combine.bind(load.bind(3), load.bind(4))
    assert workflow.run(dag, workflow_id="wf-basic") == 14
    assert workflow.get_status("wf-basic")["steps_completed"] == 3

    # Re-running the SAME workflow id re-executes NOTHING (exactly-once):
    # every step loads from storage.
    assert workflow.run(dag, workflow_id="wf-basic") == 14
    assert marker.read_text().count("ran") == 1


def test_resume_skips_completed_steps(cluster, tmp_path):
    progress = tmp_path / "progress.txt"

    @workflow.step
    def stage(name, upstream=None):
        with open(progress, "a") as f:
            f.write(name + "\n")
        if name == "c" and not os.path.exists(tmp_path / "allow_c"):
            raise RuntimeError("c not allowed yet")
        return name

    a = stage.options(max_retries=1).bind("a")
    b = stage.options(max_retries=1).bind("b", upstream=a)
    c = stage.options(max_retries=1).bind("c", upstream=b)

    with pytest.raises(RuntimeError, match="failed after"):
        workflow.run(c, workflow_id="wf-resume")
    # a and b completed + checkpointed; c failed.
    assert workflow.get_status("wf-resume")["steps_completed"] == 2

    (tmp_path / "allow_c").write_text("ok")
    assert workflow.resume("wf-resume", c) == "c"
    # a/b never re-ran: one line each; c ran once per attempt.
    lines = progress.read_text().splitlines()
    assert lines.count("a") == 1 and lines.count("b") == 1


def test_resume_rejects_different_dag(cluster):
    @workflow.step
    def s(x):
        return x

    workflow.run(s.bind(1), workflow_id="wf-mismatch")
    with pytest.raises(ValueError, match="differs"):
        workflow.resume("wf-mismatch", s.bind(2))


def test_diamond_dag_shares_step(cluster, tmp_path):
    counter = tmp_path / "n.txt"

    @workflow.step
    def base():
        with open(counter, "a") as f:
            f.write("x")
        return 10

    @workflow.step
    def left(v):
        return v + 1

    @workflow.step
    def right(v):
        return v + 2

    @workflow.step
    def join(l, r):
        return l * r

    b = base.bind()
    dag = join.bind(left.bind(b), right.bind(b))
    assert workflow.run(dag, workflow_id="wf-diamond") == 11 * 12
    # The shared base step executed ONCE (diamond dedup via step ids).
    assert counter.read_text() == "x"


def test_dynamic_continuation(cluster):
    """A step returning workflow.continuation(...) extends the DAG at
    runtime (reference: dynamic workflows); the final value checkpoints
    under the ORIGINAL step so resume never replays."""
    from ray_tpu import workflow

    calls = {"n": 0}

    @workflow.step
    def double(x):
        return x * 2

    @workflow.step
    def maybe_expand(x):
        if x < 8:
            return workflow.continuation(maybe_expand.bind(
                workflow.StepNode(double._fn, (x,), {}, "double", 3)))
        return x

    out = workflow.run(maybe_expand.bind(1), workflow_id="wf-dyn")
    assert out == 8  # 1 -> 2 -> 4 -> 8 through dynamic expansion


def test_events_wait_and_send(cluster):
    """wait_for_event blocks a branch until send_event delivers; the
    payload checkpoints durably (a resumed run does not re-wait)."""
    import threading
    import time as _time

    from ray_tpu import workflow

    @workflow.step
    def combine(a, ev):
        return {"a": a, "event": ev}

    @workflow.step
    def base():
        return 10

    dag = combine.bind(base.bind(),
                       workflow.wait_for_event("go", timeout=30))

    def sender():
        _time.sleep(1.0)
        workflow.send_event("wf-ev", "go", {"ok": True})

    t = threading.Thread(target=sender)
    t.start()
    out = workflow.run(dag, workflow_id="wf-ev")
    t.join()
    assert out == {"a": 10, "event": {"ok": True}}
    # Resume: event result is checkpointed; completes instantly.
    out2 = workflow.resume("wf-ev", dag)
    assert out2 == out


def test_independent_branches_run_concurrently(cluster):
    """Two slow sibling branches complete in ~1x branch time, not 2x
    (reference: workflow_executor runs ready steps concurrently)."""
    import time as _time

    @workflow.step
    def slow(tag):
        _time.sleep(1.5)
        return tag

    @workflow.step
    def join(a, b):
        return a + b

    dag = join.bind(slow.bind("l"), slow.bind("r"))
    t0 = _time.monotonic()
    assert workflow.run(dag, workflow_id="wf-par") == "lr"
    elapsed = _time.monotonic() - t0
    # Serial execution would take >= 3.0s; concurrent ~1.5s + overhead.
    assert elapsed < 2.8, f"branches ran serially ({elapsed:.1f}s)"


def test_run_async_list_and_status(cluster):
    import time as _time

    @workflow.step
    def gate(path):
        while not os.path.exists(path):
            _time.sleep(0.05)
        return "done"

    gate_path = os.path.join(
        os.environ["RTPU_WORKFLOW_STORAGE"], "gate-async")
    handle = workflow.run_async(gate.bind(gate_path),
                                workflow_id="wf-async")
    assert not handle.done()
    st = workflow.get_status("wf-async")
    assert st["status"] == "RUNNING"
    listed = {w["workflow_id"]: w for w in workflow.list_all()}
    assert listed["wf-async"]["status"] == "RUNNING"
    with open(gate_path, "w") as f:
        f.write("go")
    assert handle.result(timeout=30) == "done"
    assert workflow.get_status("wf-async")["status"] == "SUCCEEDED"
    assert {w["workflow_id"] for w in
            workflow.list_all(status_filter="SUCCEEDED")} >= {"wf-async"}


def test_cancel_running_workflow(cluster):
    import time as _time

    @workflow.step
    def forever():
        _time.sleep(600)
        return "never"

    handle = workflow.run_async(forever.bind(), workflow_id="wf-cancel")
    _time.sleep(0.5)  # let the step launch
    workflow.cancel("wf-cancel")
    with pytest.raises(workflow.WorkflowCancelledError):
        handle.result(timeout=30)
    assert workflow.get_status("wf-cancel")["status"] == "CANCELED"


def test_retry_exceptions_discriminates(cluster, tmp_path):
    """retry_exceptions=False: a deterministic user bug runs the step
    ONCE (no side-effect replay); an allowlisted type still retries."""
    no_retry_marker = tmp_path / "noretry.txt"

    @workflow.step(max_retries=3, retry_exceptions=False)
    def buggy():
        with open(no_retry_marker, "a") as f:
            f.write("ran\n")
        raise ValueError("deterministic bug")

    with pytest.raises(RuntimeError, match="failed after 1 attempts"):
        workflow.run(buggy.bind(), workflow_id="wf-noretry")
    assert no_retry_marker.read_text().count("ran") == 1

    allow_marker = tmp_path / "allow.txt"

    @workflow.step(max_retries=2, retry_exceptions=(ConnectionError,))
    def flaky():
        with open(allow_marker, "a") as f:
            f.write("ran\n")
        if allow_marker.read_text().count("ran") < 2:
            raise ConnectionError("transient")
        return "ok"

    assert workflow.run(flaky.bind(), workflow_id="wf-allow") == "ok"
    assert allow_marker.read_text().count("ran") == 2

    deny_marker = tmp_path / "deny.txt"

    @workflow.step(max_retries=3, retry_exceptions=(ConnectionError,))
    def wrong_type():
        with open(deny_marker, "a") as f:
            f.write("ran\n")
        raise KeyError("not allowlisted")

    with pytest.raises(RuntimeError, match="failed after 1 attempts"):
        workflow.run(wrong_type.bind(), workflow_id="wf-deny")
    assert deny_marker.read_text().count("ran") == 1


def test_get_output_after_completion(cluster):
    @workflow.step
    def make():
        return {"answer": 42}

    workflow.run(make.bind(), workflow_id="wf-out")
    assert workflow.get_output("wf-out") == {"answer": 42}


def test_fsspec_memory_storage(cluster, monkeypatch):
    """Storage roots may be fsspec URLs (reference: workflow storage on
    fs/s3) — memory:// exercises the non-local path end-to-end."""
    from ray_tpu import workflow

    monkeypatch.setenv("RTPU_WORKFLOW_STORAGE", "memory://wfroot")

    @workflow.step
    def add(a, b):
        return a + b

    dag = add.bind(add.bind(1, 2), 4)
    assert workflow.run(dag, workflow_id="wf-mem") == 7
    st = workflow.get_status("wf-mem")
    assert st["steps_completed"] == 2
    assert workflow.resume("wf-mem", dag) == 7
    workflow.delete("wf-mem")
