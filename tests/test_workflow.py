"""Workflow tests: durable DAG execution + exactly-once resume
(reference analog: python/ray/workflow/tests/test_basic_workflows.py).
"""

import os

import pytest

import ray_tpu
from ray_tpu import workflow


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    os.environ["RTPU_WORKFLOW_STORAGE"] = str(
        tmp_path_factory.mktemp("workflows"))
    rt = ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield rt
    ray_tpu.shutdown()
    os.environ.pop("RTPU_WORKFLOW_STORAGE", None)


def test_dag_executes_and_checkpoints(cluster, tmp_path):
    marker = tmp_path / "count.txt"

    @workflow.step
    def load(x):
        return x * 2

    @workflow.step
    def combine(a, b):
        with open(marker, "a") as f:
            f.write("ran\n")
        return a + b

    dag = combine.bind(load.bind(3), load.bind(4))
    assert workflow.run(dag, workflow_id="wf-basic") == 14
    assert workflow.get_status("wf-basic")["steps_completed"] == 3

    # Re-running the SAME workflow id re-executes NOTHING (exactly-once):
    # every step loads from storage.
    assert workflow.run(dag, workflow_id="wf-basic") == 14
    assert marker.read_text().count("ran") == 1


def test_resume_skips_completed_steps(cluster, tmp_path):
    progress = tmp_path / "progress.txt"

    @workflow.step
    def stage(name, upstream=None):
        with open(progress, "a") as f:
            f.write(name + "\n")
        if name == "c" and not os.path.exists(tmp_path / "allow_c"):
            raise RuntimeError("c not allowed yet")
        return name

    a = stage.options(max_retries=1).bind("a")
    b = stage.options(max_retries=1).bind("b", upstream=a)
    c = stage.options(max_retries=1).bind("c", upstream=b)

    with pytest.raises(RuntimeError, match="failed after"):
        workflow.run(c, workflow_id="wf-resume")
    # a and b completed + checkpointed; c failed.
    assert workflow.get_status("wf-resume")["steps_completed"] == 2

    (tmp_path / "allow_c").write_text("ok")
    assert workflow.resume("wf-resume", c) == "c"
    # a/b never re-ran: one line each; c ran once per attempt.
    lines = progress.read_text().splitlines()
    assert lines.count("a") == 1 and lines.count("b") == 1


def test_resume_rejects_different_dag(cluster):
    @workflow.step
    def s(x):
        return x

    workflow.run(s.bind(1), workflow_id="wf-mismatch")
    with pytest.raises(ValueError, match="differs"):
        workflow.resume("wf-mismatch", s.bind(2))


def test_diamond_dag_shares_step(cluster, tmp_path):
    counter = tmp_path / "n.txt"

    @workflow.step
    def base():
        with open(counter, "a") as f:
            f.write("x")
        return 10

    @workflow.step
    def left(v):
        return v + 1

    @workflow.step
    def right(v):
        return v + 2

    @workflow.step
    def join(l, r):
        return l * r

    b = base.bind()
    dag = join.bind(left.bind(b), right.bind(b))
    assert workflow.run(dag, workflow_id="wf-diamond") == 11 * 12
    # The shared base step executed ONCE (diamond dedup via step ids).
    assert counter.read_text() == "x"
