"""Scalability harness smoke: every bench shape runs end to end at tiny
sizes and produces sane numbers (the full-size capture runs out of band
into PERF_r*.json — reference analog: release/benchmarks CI smoke)."""

import json
import subprocess
import sys

def test_harness_smoke_all_benchmarks(tmp_path):
    out = str(tmp_path / "perf.json")
    # Subprocess: the harness owns its own cluster + system config.
    proc = subprocess.run(
        [sys.executable, "-m", "ray_tpu.util.scalability", "--smoke",
         "--out", out],
        capture_output=True, text=True, timeout=800)
    assert proc.returncode == 0, proc.stderr[-2000:]
    with open(out) as f:
        report = json.load(f)
    s = report["scalability"]
    assert s["many_actors"]["num_actors"] == 50
    assert s["many_actors"]["actors_per_s"] > 1.0
    assert s["many_pgs"]["pgs_per_s"] > 1.0
    assert s["many_queued_tasks"]["end_to_end_per_s"] > 100.0
    assert s["broadcast"]["num_nodes"] == 2
    assert s["broadcast"]["broadcast_s"] < 120.0
    mc = s["multi_client_drivers"]
    assert mc["num_client_processes"] == 2
    assert mc["aggregate_tasks_per_s"] > 100.0
    assert "_meta" in s and "host" in s["_meta"]
