"""RTPU_DEBUG_CHAN witness: the dynamic half of the ``chan`` rule
family. Three injected faults — a seq gap, a late buffer mutation
(caught via the sampled frame checksum), and an unreleased spill pin
(the PR 19 reclaim race) — must each be reported online EXACTLY once,
while a clean run over both transports produces zero violations with
nonzero frames witnessed (a 0-violation verdict over 0 frames is
vacuous). Registry-level invariants (acks, cursors, Lamport clocks)
are unit-tested against the note_* API directly.
"""

import os
import uuid

import pytest

from ray_tpu.dag.ring import RingChannel
from ray_tpu.devtools import chan_debug


@pytest.fixture
def witness(monkeypatch):
    monkeypatch.setenv("RTPU_DEBUG_CHAN", "1")
    chan_debug.reset()
    yield
    chan_debug.reset()


def kinds():
    return [v["kind"] for v in chan_debug.violations()]


def _ring_pair(capacity=4, ring_bytes=8192):
    cid = uuid.uuid4().bytes
    return (RingChannel(cid, capacity=capacity, ring_bytes=ring_bytes),
            RingChannel(cid, capacity=capacity, ring_bytes=ring_bytes))


# ------------------------------------------------------- clean surface


def test_clean_ring_traffic_zero_violations(witness):
    w, r = _ring_pair()
    try:
        for i in range(40):
            w.write({"i": i}, i, timeout=10)
            assert r.read(i, timeout=10) == {"i": i}
    finally:
        w.close()
        r.close(unlink=True)
    assert chan_debug.violations() == []
    assert chan_debug.frames_witnessed() >= 40


def test_clean_peer_traffic_zero_violations(witness):
    from ray_tpu.dag.peer import CrossNodeChannel

    cid = uuid.uuid4().bytes
    rd = CrossNodeChannel(cid, capacity=8, edge="w->r")
    addr = rd.prepare_read()
    wr = CrossNodeChannel(cid, capacity=8, edge="w->r", addr=addr)
    try:
        for i in range(20):
            wr.write({"i": i}, i, timeout=10)
            assert rd.read(i, timeout=10) == {"i": i}
    finally:
        wr.close()
        rd.close()
    assert chan_debug.violations() == []
    assert chan_debug.frames_witnessed() >= 20


def test_clean_spill_roundtrip_zero_violations(witness):
    """A spill pin that settles (consumption observed) is not a
    violation at close."""
    w, r = _ring_pair()
    big = os.urandom(1 << 19)  # > dag_ring_spill_bytes: rides a side file
    try:
        w.write(big, 0, timeout=10)
        assert r.read(0, timeout=10) == big
        w.write("after", 1, timeout=10)  # cursor advance settles the pin
        assert r.read(1, timeout=10) == "after"
    finally:
        w.close()
        r.close(unlink=True)
    assert chan_debug.violations() == []


# -------------------------------------------------- injection: seq gap


def test_injected_seq_gap_reported_exactly_once(witness):
    w, r = _ring_pair()
    try:
        w.write("a", 0, timeout=10)
        w.write("b", 2, timeout=10)  # skipped seq 1: a hand-minted gap
    finally:
        w.close()
        r.close(unlink=True)
    assert kinds() == ["send-seq-gap"]
    assert chan_debug.violations()[0]["seq"] == 2


# ----------------------------------- injection: late buffer mutation


def test_injected_late_mutation_reported_exactly_once(witness):
    """Mutate the frame bytes AFTER the send published them (the
    mutate-after-send race, simulated in the shared ring): seq 0 is
    checksum-sampled, so the consume-side recompute must flag it."""
    w, r = _ring_pair()
    try:
        w.write(b"A" * 200, 0, timeout=10)
        idx = w._mm.find(b"A" * 50)
        assert idx > 0
        w._mm[idx:idx + 1] = b"B"  # the writer "mutating its buffer"
        got = r.read(0, timeout=10)
        assert got != b"A" * 200  # the reader really saw torn bytes
    finally:
        w.close()
        r.close(unlink=True)
    assert kinds() == ["payload-mismatch"]


# -------------------------------- injection: unreleased spill pin


def test_injected_unreleased_spill_pin_reported_exactly_once(
        witness, monkeypatch):
    """Resurrect the pre-PR-19 shape dynamically: the settle path is
    disabled, so the consumed spill's pin is still open when the
    writer closes — note_close must flag the reclaim race once."""
    from ray_tpu.core.config import GLOBAL_CONFIG as cfg

    monkeypatch.setattr(RingChannel, "_settle_spills",
                        lambda self, rpos: None)
    old_grace = cfg.dag_spill_reclaim_grace_s
    cfg.set("dag_spill_reclaim_grace_s", 0.05)
    w, r = _ring_pair()
    big = os.urandom(1 << 19)
    try:
        w.write(big, 0, timeout=10)
        assert r.read(0, timeout=10) == big  # consumed, never settled
        w.close()
        assert kinds() == ["spill-reclaim-race"]
    finally:
        cfg.set("dag_spill_reclaim_grace_s", old_grace)
        w.close()
        r.close(unlink=True)


# ------------------------------------------------ registry unit checks


def test_note_ack_before_consume_flagged(witness):
    chan_debug.note_consume("e@1", 0, 0, 0, b"x")
    chan_debug.note_ack("e@1", 0)  # fine: consumed
    chan_debug.note_ack("e@1", 3)  # phantom credit
    assert kinds() == ["ack-before-consume"]


def test_note_cursor_regression_flagged(witness):
    chan_debug.note_cursor("e@1", "wpos", 128)
    chan_debug.note_cursor("e@1", "wpos", 256)
    chan_debug.note_cursor("e@1", "wpos", 64)
    assert kinds() == ["cursor-regression"]


def test_note_send_duplicate_flagged(witness):
    chan_debug.note_send("e@1", 0, 10)
    chan_debug.note_send("e@1", 1, 10)
    chan_debug.note_send("e@1", 1, 10)
    assert kinds() == ["send-seq-duplicate"]


def test_note_send_credit_overrun_flagged(witness):
    chan_debug.note_send("e@1", 9, 10, window=(0, 4))
    assert kinds() == ["credit-overrun"]


def test_clock_inversion_flagged(witness):
    chan_debug.note_consume("e@1", 0, 7, 0, b"x")
    chan_debug.note_consume("e@1", 1, 5, 0, b"x")  # stamp went backwards
    assert kinds() == ["clock-inversion"]


def test_lamport_merge_advances_process_clock(witness):
    chan_debug.note_consume("e@1", 0, 1000, 0, b"x")
    assert chan_debug.clock_stamp("e@2") > 1000


def test_endpoint_tokens_isolate_reopened_channels(witness):
    """A reopened channel restarts seqs at 0 under the SAME edge name —
    distinct endpoint tokens keep that from tripping monotonicity."""
    chan_debug.note_send("edge@aaa", 5, 10)
    chan_debug.note_send("edge@bbb", 0, 10)  # fresh incarnation
    assert chan_debug.violations() == []


# ----------------------------------------------------- off by default


def test_zero_overhead_when_off(monkeypatch):
    monkeypatch.delenv("RTPU_DEBUG_CHAN", raising=False)
    chan_debug.reset()
    assert chan_debug.clock_stamp("e@1") == 0
    assert chan_debug.payload_crc(0, b"payload") == 0
    chan_debug.note_send("e@1", 9, 10, window=(0, 1))
    chan_debug.note_consume("e@1", 3, 1, 1, b"x")
    chan_debug.note_ack("e@1", 7)
    assert chan_debug.violations() == []
    assert chan_debug.frames_witnessed() == 0
    w, r = _ring_pair()
    try:
        w.write("x", 0)
        assert r.read(0, timeout=10) == "x"
    finally:
        w.close()
        r.close(unlink=True)
    assert chan_debug.frames_witnessed() == 0  # transports skipped hooks


# ----------------------------------------------------------- reporting


def test_report_and_dump_payload_shapes(witness):
    w, r = _ring_pair()
    try:
        w.write("x", 0, timeout=10)
        assert r.read(0, timeout=10) == "x"
    finally:
        w.close()
        r.close(unlink=True)
    rep = chan_debug.report()
    assert rep["frames"] >= 1 and rep["violations"] == 0
    assert rep["edges"]  # per-endpoint stream state present
    dump = chan_debug.dump_payload()
    assert set(dump) == {"frames", "edges", "open_pins", "violations"}
    assert dump["open_pins"] == 0


def test_flight_recorder_carries_chan_debug(witness):
    from ray_tpu.util import flight_recorder

    payload = flight_recorder.dump_payload()
    assert "chan_debug" in payload
    assert set(payload["chan_debug"]) == {"frames", "edges",
                                          "open_pins", "violations"}
