"""IMPALA (V-trace async actor-learner) + multi-agent runner tests
(reference analog: rllib/algorithms/impala/tests/ + multi-agent env runner
tests)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib.impala import IMPALA, IMPALAConfig, IMPALALearner
from ray_tpu.rllib.multi_agent import (IndependentEnsembleEnv,
                                       MultiAgentEnvRunner,
                                       MultiAgentPPO, MultiAgentPPOConfig)


@pytest.fixture(scope="module")
def cluster():
    rt = ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield rt
    ray_tpu.shutdown()


def test_vtrace_on_policy_reduces_to_discounted_return():
    """With on-policy data (ratio == 1) and no termination inside the
    window, V-trace targets telescope to the discounted bootstrap return:
    vs_t = sum_k gamma^k r_{t+k} + gamma^{T-t} V(x_T)."""
    import jax.numpy as jnp

    learner = IMPALALearner(4, 2, gamma=0.9, seed=0)
    T, B = 5, 2
    values = jnp.asarray(np.linspace(0.0, 1.0, T * B).reshape(T, B),
                         jnp.float32)
    last_value = jnp.asarray([2.0, 3.0], jnp.float32)
    batch = {
        "rewards": jnp.ones((T, B), jnp.float32),
        "terminated": jnp.zeros((T, B), jnp.float32),
        "truncated": jnp.zeros((T, B), jnp.float32),
        "bootstrap_value": jnp.zeros((T, B), jnp.float32),
    }
    rho = jnp.ones((T, B), jnp.float32)
    vs, pg_adv = learner._vtrace(values, last_value, batch, rho)

    g = 0.9
    expected = np.zeros((T, B))
    for t in range(T):
        ret = sum(g ** k for k in range(T - t))  # unit rewards
        expected[t] = ret + g ** (T - t) * np.asarray(last_value)
    np.testing.assert_allclose(np.asarray(vs), expected, rtol=1e-5)
    # pg advantage at t uses vs_{t+1}: rho * (r + gamma*vs_next - V)
    vs_next = np.concatenate([np.asarray(vs)[1:],
                              np.asarray(last_value)[None]], 0)
    np.testing.assert_allclose(
        np.asarray(pg_adv), 1.0 + g * vs_next - np.asarray(values),
        rtol=1e-5)


def test_vtrace_termination_zeroes_continuation():
    """A terminated step must not leak the next state's value into targets."""
    import jax.numpy as jnp

    learner = IMPALALearner(4, 2, gamma=0.9, seed=0)
    T, B = 3, 1
    values = jnp.zeros((T, B), jnp.float32)
    last_value = jnp.asarray([100.0], jnp.float32)
    term = jnp.zeros((T, B), jnp.float32).at[1, 0].set(1.0)
    batch = {
        "rewards": jnp.ones((T, B), jnp.float32),
        "terminated": term,
        "truncated": jnp.zeros((T, B), jnp.float32),
        "bootstrap_value": jnp.zeros((T, B), jnp.float32),
    }
    vs, _ = learner._vtrace(values, last_value, batch,
                            jnp.ones((T, B), jnp.float32))
    # t=1 terminates: vs_1 = r = 1 exactly; t=0 = 1 + 0.9*1.
    np.testing.assert_allclose(np.asarray(vs)[:2, 0], [1.9, 1.0], rtol=1e-5)
    # t=2 (fresh episode) bootstraps the big last_value.
    assert float(vs[2, 0]) > 50.0


def test_impala_local_learning_gate():
    """Learning-regression gate: V-trace actor-critic clears a CartPole
    return bar within a bounded budget (reference: IMPALA CartPole tuned
    example). Single-pass updates learn slower than PPO's 4-epoch loop,
    so the bar is lower and the budget bigger."""
    algo = (IMPALAConfig()
            .environment("CartPole")
            .env_runners(num_env_runners=0, num_envs_per_env_runner=16,
                         rollout_fragment_length=128)
            .training(lr=1e-3, entropy_coeff=0.01)
            .build())
    best = 0.0
    for _ in range(150):
        result = algo.train()
        ret = result["env_runners"]["episode_return_mean"]
        if ret is not None:
            best = max(best, ret)
        if best >= 150.0:
            break
    assert best >= 150.0, f"IMPALA failed to reach 150 (best {best})"


def test_impala_async_runners(cluster):
    """Async pipeline: 2 remote runners stay armed; each training_step
    consumes exactly one rollout and re-arms its runner."""
    algo = (IMPALAConfig()
            .environment("CartPole")
            .env_runners(num_env_runners=2, num_envs_per_env_runner=4,
                         rollout_fragment_length=32)
            .build())
    try:
        assert len(algo._inflight) == 2
        for _ in range(4):
            stats = algo.training_step()
            assert np.isfinite(stats["total_loss"])
            assert len(algo._inflight) == 2  # re-armed
        assert algo._total_steps == 4 * 32 * 4
    finally:
        algo.stop()


# ---------------------------------------------------------------- multi-agent


def test_multi_agent_runner_shapes():
    def ctor(num_envs, seed):
        return IndependentEnsembleEnv(
            {"a0": "CartPole", "a1": "CartPole"}, num_envs=num_envs,
            seed=seed)

    runner = MultiAgentEnvRunner(ctor, num_envs=4, rollout_len=8,
                                 policy_mapping={"a0": "p0", "a1": "p0"},
                                 seed=0)
    from ray_tpu.rllib import models
    import jax

    params = models.init_policy_params(jax.random.PRNGKey(0), 4, 2, 32)
    runner.set_weights({"p0": params})
    batch = runner.sample()
    assert set(batch) == {"a0", "a1"}
    for a in ("a0", "a1"):
        assert batch[a]["obs"].shape == (8, 4, 4)
        assert batch[a]["actions"].shape == (8, 4)
        assert batch[a]["last_value"].shape == (4,)
    metrics = runner.get_metrics()
    assert set(metrics) == {"a0", "a1"}


def test_multi_agent_ppo_parameter_sharing_learns():
    """Two agents share one policy id: pooled experience, one learner.
    The shared policy must improve on CartPole (multi-agent learning
    gate; pooling doubles the batch so the budget stays small)."""
    def ctor(num_envs, seed):
        return IndependentEnsembleEnv(
            {"a0": "CartPole", "a1": "CartPole"}, num_envs=num_envs,
            seed=seed)

    algo = MultiAgentPPOConfig(
        env=ctor, policies=("shared",),
        policy_mapping={"a0": "shared", "a1": "shared"},
        num_env_runners=0, num_envs_per_runner=8, rollout_len=128,
        minibatch_size=512, seed=0).build()
    best = 0.0
    for _ in range(40):
        result = algo.train()
        rets = [m["episode_return_mean"]
                for m in result["env_runners"].values()
                if m["episode_return_mean"] is not None]
        if rets:
            best = max(best, float(np.mean(rets)))
        if best >= 100.0:
            break
    assert best >= 100.0, f"shared policy failed to reach 100 (best {best})"
    assert set(algo.get_weights()) == {"shared"}
