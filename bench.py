"""Benchmark: Llama-class pretrain step on the available TPU chip(s).

Prints ONE JSON line:
  {"metric": "train_mfu_llama1b", "value": <MFU>, "unit": "mfu",
   "vs_baseline": <MFU / 0.40>, ...extras}

The north-star target from BASELINE.json is >=40% MFU on Llama-class
pretrain (reference has no TPU/LLM numbers checked in; 0.40 is the target
ratio denominator). Extras report tokens/s/chip for context.

Structure: the measurement runs in a CHILD subprocess (``--child``); the
parent supervises with retry + backoff. Rationale: a TPU backend init
failure is cached for the life of a JAX process, so retrying in-process
is useless — and the round-3 driver run lost its only hardware number to
exactly one flaky init. On persistent failure the parent diagnoses which
processes hold the TPU device files and emits a structured failure record
(still one JSON line) instead of a traceback.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

# Peak dense bf16 FLOP/s per chip by device kind substring.
PEAK_FLOPS = [
    ("v5 lite", 197e12),
    ("v5e", 197e12),
    ("v5p", 459e12),
    ("v4", 275e12),
    ("v6 lite", 918e12),
    ("v6e", 918e12),
    ("cpu", 1e12),  # nominal, CI fallback
]

ATTEMPTS = 4
BACKOFFS_S = (10, 30, 60)  # between attempts
CHILD_TIMEOUT_S = 1500     # first TPU compile can take minutes
PROBE_TIMEOUT_S = 180      # backend init probe (axon can HANG, not fail)


def peak_flops_for(device_kind: str) -> float:
    dk = device_kind.lower()
    for key, val in PEAK_FLOPS:
        if key in dk:
            return val
    return 197e12


def child_main() -> None:
    import numpy as np

    _pin_platform()
    import jax

    from ray_tpu.models import llama
    from ray_tpu.parallel import spmd
    from ray_tpu.parallel.mesh import MeshSpec, make_mesh

    devices = jax.devices()
    n_chips = len(devices)
    on_tpu = devices[0].platform == "tpu"
    kind = devices[0].device_kind

    if on_tpu:
        cfg = llama.LLAMA3_1B
        batch, seq = 8, 2048
        cfg = llama.LlamaConfig(
            **{**cfg.__dict__, "max_seq_len": seq}
        )
        warmup, iters = 2, 10
    else:
        cfg = llama.tiny_config(max_seq_len=256)
        batch, seq = 4, 256
        warmup, iters = 1, 3

    mesh = make_mesh(MeshSpec(fsdp=n_chips), devices) if n_chips > 1 else \
        make_mesh(MeshSpec(), devices[:1])
    tx = spmd.default_optimizer(lr=1e-4)

    with jax.sharding.set_mesh(mesh):
        state = spmd.sharded_init(cfg, mesh, jax.random.PRNGKey(0), tx)
        step = spmd.make_train_step(cfg, mesh, tx)
        rng = np.random.default_rng(0)
        tokens = jax.device_put(
            rng.integers(0, cfg.vocab_size, (batch, seq)).astype(np.int32),
            spmd.data_sharding(mesh),
        )
        # NOTE: through the remote-TPU tunnel, block_until_ready is not a
        # reliable execution barrier — only a host fetch is. Fetch the loss
        # scalar once per timed region (per-fetch overhead ~75ms, amortized
        # over `iters` steps).
        for _ in range(warmup):
            state, metrics = step(state, tokens)
        float(metrics["loss"])
        t0 = time.perf_counter()
        for _ in range(iters):
            state, metrics = step(state, tokens)
        loss = float(metrics["loss"])
        dt = time.perf_counter() - t0
        assert np.isfinite(loss), f"non-finite loss {loss}"

    tokens_per_s = batch * seq * iters / dt
    tokens_per_s_chip = tokens_per_s / n_chips
    flops_tok = cfg.flops_per_token(seq)
    mfu = tokens_per_s_chip * flops_tok / peak_flops_for(kind)

    print(json.dumps({
        "metric": "train_mfu_llama1b",
        "value": round(mfu, 4),
        "unit": "mfu",
        "vs_baseline": round(mfu / 0.40, 4),
        "tokens_per_s_per_chip": round(tokens_per_s_chip, 1),
        "step_time_s": round(dt / iters, 4),
        "device": kind,
        "n_chips": n_chips,
        "config": "llama3-1b" if on_tpu else "tiny-cpu",
        "batch": batch,
        "seq": seq,
    }))


def accel_holders() -> list:
    """Which processes hold TPU device files open (/dev/accel*, /dev/vfio*).
    A wedged holder from a previous run is the usual cause of
    'UNAVAILABLE: TPU backend setup/compile error'."""
    holders = []
    try:
        for pid in os.listdir("/proc"):
            if not pid.isdigit():
                continue
            fd_dir = f"/proc/{pid}/fd"
            try:
                for fd in os.listdir(fd_dir):
                    try:
                        tgt = os.readlink(os.path.join(fd_dir, fd))
                    except OSError:
                        continue
                    if "/dev/accel" in tgt or "/dev/vfio" in tgt:
                        try:
                            with open(f"/proc/{pid}/cmdline", "rb") as f:
                                cmd = f.read().replace(b"\0", b" ") \
                                    .decode(errors="replace").strip()[:200]
                        except OSError:
                            cmd = "?"
                        holders.append(
                            {"pid": int(pid), "device": tgt, "cmd": cmd})
                        break
            except OSError:
                continue
    except OSError:
        pass
    return holders


def _pin_platform() -> None:
    """The axon TPU plugin force-appends itself to jax_platforms at import
    time, overriding JAX_PLATFORMS=cpu — and a wedged tunnel then HANGS
    backend init. Honor an explicit cpu request."""
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")


def probe_main() -> None:
    """Cheap backend-liveness check: init + one tiny computation."""
    _pin_platform()
    import jax
    import jax.numpy as jnp

    d = jax.devices()
    x = float(jnp.ones(8).sum())
    assert x == 8.0
    print(f"probe-ok {d[0].platform} {d[0].device_kind}")


def _run(args: list, timeout_s: int):
    return subprocess.run(
        [sys.executable, os.path.abspath(__file__)] + args,
        capture_output=True, text=True, timeout=timeout_s,
        cwd=os.path.dirname(os.path.abspath(__file__)))


def main() -> int:
    errors = []
    for attempt in range(ATTEMPTS):
        # Phase 1: probe. A wedged axon tunnel HANGS in init (observed:
        # >20min asleep in nanosleep) rather than raising — without this,
        # each dead attempt burns the full measurement timeout.
        try:
            probe = _run(["--probe"], PROBE_TIMEOUT_S)
            if probe.returncode != 0:
                tail = (probe.stderr or probe.stdout).strip() \
                    .splitlines()[-4:]
                raise RuntimeError("probe rc=%d: %s"
                                   % (probe.returncode, " | ".join(tail)))
        except (subprocess.TimeoutExpired, RuntimeError) as e:
            msg = (f"attempt {attempt}: probe hang >{PROBE_TIMEOUT_S}s"
                   if isinstance(e, subprocess.TimeoutExpired) else
                   f"attempt {attempt}: {e}")
            errors.append(msg)
            print(msg + "; backing off", file=sys.stderr)
            if attempt < ATTEMPTS - 1:
                time.sleep(BACKOFFS_S[min(attempt, len(BACKOFFS_S) - 1)])
            continue
        # Phase 2: measurement.
        try:
            proc = _run(["--child"], CHILD_TIMEOUT_S)
        except subprocess.TimeoutExpired:
            errors.append(f"attempt {attempt}: timeout {CHILD_TIMEOUT_S}s")
            continue
        if proc.returncode == 0:
            # Forward exactly the child's JSON line.
            line = [ln for ln in proc.stdout.splitlines()
                    if ln.startswith("{")][-1]
            print(line)
            return 0
        tail = (proc.stderr or proc.stdout).strip().splitlines()[-6:]
        errors.append(f"attempt {attempt} rc={proc.returncode}: "
                      + " | ".join(tail))
        print(f"bench attempt {attempt} failed (rc={proc.returncode}); "
              f"retrying", file=sys.stderr)
        if attempt < ATTEMPTS - 1:
            time.sleep(BACKOFFS_S[min(attempt, len(BACKOFFS_S) - 1)])
    # Persistent failure: structured record, not a traceback. value 0.0
    # plus an explicit error field — never a silently-plausible number.
    print(json.dumps({
        "metric": "train_mfu_llama1b",
        "value": 0.0,
        "unit": "mfu",
        "vs_baseline": 0.0,
        "error": "TPU backend init failed after retries",
        "attempts": ATTEMPTS,
        "attempt_errors": errors[-2:],
        "accel_holders": accel_holders(),
    }))
    return 1


if __name__ == "__main__":
    if "--child" in sys.argv:
        sys.exit(child_main())
    if "--probe" in sys.argv:
        sys.exit(probe_main())
    sys.exit(main())
