"""Benchmark: Llama-class pretrain step on the available TPU chip(s).

Prints ONE JSON line:
  {"metric": "train_mfu_llama1b", "value": <MFU>, "unit": "mfu",
   "vs_baseline": <MFU / 0.40>, ...extras}

The north-star target from BASELINE.json is >=40% MFU on Llama-class
pretrain (reference has no TPU/LLM numbers checked in; 0.40 is the target
ratio denominator). Extras report tokens/s/chip for context.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

# Peak dense bf16 FLOP/s per chip by device kind substring.
PEAK_FLOPS = [
    ("v5 lite", 197e12),
    ("v5e", 197e12),
    ("v5p", 459e12),
    ("v4", 275e12),
    ("v6 lite", 918e12),
    ("v6e", 918e12),
    ("cpu", 1e12),  # nominal, CI fallback
]


def peak_flops_for(device_kind: str) -> float:
    dk = device_kind.lower()
    for key, val in PEAK_FLOPS:
        if key in dk:
            return val
    return 197e12


def main() -> None:
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import llama
    from ray_tpu.parallel import spmd
    from ray_tpu.parallel.mesh import MeshSpec, make_mesh

    devices = jax.devices()
    n_chips = len(devices)
    on_tpu = devices[0].platform == "tpu"
    kind = devices[0].device_kind

    if on_tpu:
        cfg = llama.LLAMA3_1B
        batch, seq = 8, 2048
        cfg = llama.LlamaConfig(
            **{**cfg.__dict__, "max_seq_len": seq}
        )
        warmup, iters = 2, 10
    else:
        cfg = llama.tiny_config(max_seq_len=256)
        batch, seq = 4, 256
        warmup, iters = 1, 3

    mesh = make_mesh(MeshSpec(fsdp=n_chips), devices) if n_chips > 1 else \
        make_mesh(MeshSpec(), devices[:1])
    tx = spmd.default_optimizer(lr=1e-4)

    with jax.sharding.set_mesh(mesh):
        state = spmd.sharded_init(cfg, mesh, jax.random.PRNGKey(0), tx)
        step = spmd.make_train_step(cfg, mesh, tx)
        rng = np.random.default_rng(0)
        tokens = jax.device_put(
            rng.integers(0, cfg.vocab_size, (batch, seq)).astype(np.int32),
            spmd.data_sharding(mesh),
        )
        # NOTE: through the remote-TPU tunnel, block_until_ready is not a
        # reliable execution barrier — only a host fetch is. Fetch the loss
        # scalar once per timed region (per-fetch overhead ~75ms, amortized
        # over `iters` steps).
        for _ in range(warmup):
            state, metrics = step(state, tokens)
        float(metrics["loss"])
        t0 = time.perf_counter()
        for _ in range(iters):
            state, metrics = step(state, tokens)
        loss = float(metrics["loss"])
        dt = time.perf_counter() - t0
        assert np.isfinite(loss), f"non-finite loss {loss}"

    tokens_per_s = batch * seq * iters / dt
    tokens_per_s_chip = tokens_per_s / n_chips
    flops_tok = cfg.flops_per_token(seq)
    mfu = tokens_per_s_chip * flops_tok / peak_flops_for(kind)

    print(json.dumps({
        "metric": "train_mfu_llama1b",
        "value": round(mfu, 4),
        "unit": "mfu",
        "vs_baseline": round(mfu / 0.40, 4),
        "tokens_per_s_per_chip": round(tokens_per_s_chip, 1),
        "step_time_s": round(dt / iters, 4),
        "device": kind,
        "n_chips": n_chips,
        "config": "llama3-1b" if on_tpu else "tiny-cpu",
        "batch": batch,
        "seq": seq,
    }))


if __name__ == "__main__":
    sys.exit(main())
