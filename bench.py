"""Benchmark: BOTH north-star metrics (BASELINE.md) on the available chip.

Prints one JSON line per row, then ONE final merged line (the driver
records the tail line):

  {"metric": "train_mfu_llama8b_proxy", "value": <MFU>, "unit": "mfu",
   "vs_baseline": <MFU/0.40>, "train_mfu_llama1b": ...,
   "llm_decode_tokens_per_s": ..., "serve_llm_requests_per_s": ...,
   "serve_llm_p50_ttft_ms": ..., "serve_llm_p99_ttft_ms": ..., ...}

Rows:
- train_mfu_llama1b — full Llama-3-1B pretrain step, measured directly.
- train_mfu_llama8b_proxy — 8B-class MFU via a two-depth layer scan:
  one v5e chip (16 GB HBM) cannot hold 8B params + optimizer state, so
  the step is measured at two depths of the TRUE 8B layer geometry
  (d=4096, d_ff=14336, GQA 32/8, vocab 128k, seq 2048, full remat,
  chunked CE, SGD) and the per-layer time from the depth differential is
  extrapolated to 32 layers. The differential cancels the embed/head/CE
  cost shared by both runs; method fields are recorded in the row.
- llm_decode_tokens_per_s — the native continuous-batching engine
  (serve/engine/) decoding with Llama-1B weights on the chip.
- llm_engine — the engine suite (``--engine`` runs it standalone):
  decode tok/s, engine-side TTFT/TPOT p50, and prefix-cache hit rate
  under a shared-prefix workload; rows are labelled ``config:
  "tiny-cpu"`` when not measured on hardware.
- llm_engine_spec / llm_engine_spec_off — speculative decoding
  (prompt-lookup drafting + multi-token verify) on a repetitive
  workload, measured against the identical engine with speculation
  disabled: tok/s both ways, ``llm_spec_accept_rate``, and the
  ``spec_speedup`` ratio (greedy outputs are token-identical, so both
  rows count the same tokens).
- ops_microbench / decode_matmul_gbps — per-kernel rows (``--ops`` runs
  them standalone): fused-vs-unfused step time for the model-path glue
  (RMSNorm / rope / SwiGLU, ops/fused.py) and the decode-shaped matmul's
  weight-streaming GB/s at the working dtype vs weight-only int8
  (``baseline_dtype`` names the precision) — so a kernel
  regression is visible in BENCH_r0N without a full train run.
- llm_decode_tokens_per_s_int8 — the decode bench re-run with
  ``quantize="int8"`` (weight-only int8, models/quant.py) on an
  otherwise identical engine; carries ``speedup_vs_f32``.
- serve_llm_* — req/s + p50/p99 TTFT through the FULL serve stack
  (controller/router/replica, tiny engine) in a CPU child process; the
  reference publishes no serve numbers (it delegates to vLLM), so these
  are absolute, tracked round-over-round.
- locality_scheduling — locality-aware scheduling suite (``--locality``
  runs it standalone) on a 4-node in-process CPU cluster:
  ``locality_hit_rate`` and ``object_bytes_pulled_per_task`` for the
  default scheduler vs a forced-random-placement baseline of the same
  workload.
- chaos_recovery — fault-recovery suite (``--chaos`` runs it
  standalone) on a real subprocess cluster: ``head_recovery_s`` (the
  head is SIGKILLed mid-workload; time until a NEW head-dependent
  submission — an actor creation — completes against the respawned
  head), ``object_reconstruction_s`` (the only holder of a task output
  is SIGKILLed; time for ``get()`` to complete via lineage
  re-execution), ``leaked_leases`` (the post-drain open-lease census
  over every node, which must be 0), and ``leaked_resources`` (the
  RTPU_DEBUG_RES cluster-wide acquire/release balance — BufferLease
  pins, node lease-table entries, KV reservations — aggregated over
  dump_flight, which must also be 0; the child always runs under
  RTPU_DEBUG_RPC=1 + RTPU_DEBUG_RES=1). Needs a loadable native store
  lib like the dataplane suite.
- dataplane — multi-writer object-plane suite (``--dataplane`` runs it
  standalone): K-process concurrent large puts through one sharded shm
  store (``single_put_gbps``, ``multi_put_gbps``, ``put_scaling_ratio``
  = multi/single — concurrent writers must not fall below one), node-to-
  node pull bandwidth over the scatter-gather transfer path
  (``pull_gbps``), and n-callers x n-actors calls with array args
  (``actor_args_nn_per_s``). Needs a loadable native store lib
  (RTPU_SHM_STORE_SO on containers whose glibc rejects the checked-in
  .so).
- data — streaming Dataset executor suite (``--data`` standalone):
  same-window alternating A/B of ``random_shuffle`` with the exchange
  on the channel mesh vs per-task RPC (``data_shuffle_gbps_channel`` /
  ``_task`` / ``data_shuffle_channel_speedup``), and a synthetic train
  loop over ``iter_batches(device_put=...)`` with the double-buffered
  loader vs inline transfers (``data_ingest_steps_per_s_buffered`` /
  ``_inline`` / ``data_ingest_overlap_speedup``) plus a pre-staged
  roofline (``data_ingest_efficiency``; ``cpu_cores`` on the row —
  overlap > 1 needs host cores for the loader thread). Needs the
  native store lib, like dataplane.

Structure: measurements run in CHILD subprocesses; the parent supervises
with retry + backoff. A TPU backend init failure is cached for the life
of a JAX process, so retrying in-process is useless — and a wedged axon
tunnel HANGS rather than fails, hence the probe phase. On persistent
failure the parent emits a structured failure record (still one JSON
line) instead of a traceback.
"""

from __future__ import annotations

import dataclasses
import json
import os
import subprocess
import sys
import time
import uuid

# Peak dense bf16 FLOP/s per chip by device kind substring.
PEAK_FLOPS = [
    ("v5 lite", 197e12),
    ("v5e", 197e12),
    ("v5p", 459e12),
    ("v4", 275e12),
    ("v6 lite", 918e12),
    ("v6e", 918e12),
    ("cpu", 1e12),  # nominal, CI fallback
]

ATTEMPTS = 4
BACKOFFS_S = (10, 30, 60)  # between attempts
CHILD_TIMEOUT_S = 2100     # first TPU compiles (4 programs) can take minutes
SERVE_TIMEOUT_S = 900
SERVE_ROUTED_TIMEOUT_S = 600  # whole 8-phase sweep child (2 replicas, CPU)
PROBE_TIMEOUT_S = 180      # backend init probe (axon can HANG, not fail)
LOCALITY_TIMEOUT_S = 420   # per locality child (boots a 4-node cluster)
DATAPLANE_TIMEOUT_S = 420  # dataplane child (store bench + 2-node cluster)
CHAOS_TIMEOUT_S = 600      # chaos child (kill head/node + upgrade + recover)
SCALE_TIMEOUT_S = 300      # scale child (100 simulated nodes, head hot paths)
DAG_TIMEOUT_S = 420        # dag child (2-actor cluster, channel vs RPC hops)
DATA_TIMEOUT_S = 420       # data child (channel-vs-task shuffle + ingest A/B)
DISAGG_TIMEOUT_S = 900     # disagg serve sweep (colocated vs disagg TTFT)
KV_FLEET_TIMEOUT_S = 600   # fleet KV tier A/B (spill/pull vs recompute)
SERVE_SCALE_TIMEOUT_S = 900  # serve-scale suite (router sim + QoS flood
#                              + streaming disagg A/B cluster)


def peak_flops_for(device_kind: str) -> float:
    dk = device_kind.lower()
    for key, val in PEAK_FLOPS:
        if key in dk:
            return val
    return 197e12


# --------------------------------------------------------------------------
# train + decode child (owns the TPU)
# --------------------------------------------------------------------------

def _timed_steps(step, state, tokens, warmup: int, iters: int):
    """Returns (seconds_per_step, last_loss). Through the remote-TPU
    tunnel block_until_ready is not a reliable barrier — only a host
    fetch is; fetch the loss scalar once per timed region."""
    for _ in range(warmup):
        state, metrics = step(state, tokens)
    float(metrics["loss"])
    t0 = time.perf_counter()
    for _ in range(iters):
        state, metrics = step(state, tokens)
    loss = float(metrics["loss"])
    dt = time.perf_counter() - t0
    return dt / iters, loss, state


def _bench_train(cfg, batch, seq, warmup, iters, devices, tx=None):
    import numpy as np

    import jax

    from ray_tpu.parallel import spmd
    from ray_tpu.parallel.mesh import MeshSpec, make_mesh, mesh_context

    n = len(devices)
    mesh = make_mesh(MeshSpec(fsdp=n), devices) if n > 1 else \
        make_mesh(MeshSpec(), devices[:1])
    tx = tx or spmd.default_optimizer(lr=1e-4)
    # ONE host key, created outside any mesh context (jax-lint
    # rng-reinit-per-mesh: jax<0.5 jitted RNG values depend on
    # out_shardings, so per-mesh re-init breaks equivalence checks).
    key = jax.random.PRNGKey(0)
    with mesh_context(mesh):
        state = spmd.sharded_init(cfg, mesh, key, tx)
        step = spmd.make_train_step(cfg, mesh, tx)
        rng = np.random.default_rng(0)
        tokens = jax.device_put(
            rng.integers(0, cfg.vocab_size, (batch, seq)).astype(np.int32),
            spmd.data_sharding(mesh))
        step_s, loss, state = _timed_steps(step, state, tokens, warmup, iters)
    assert np.isfinite(loss), f"non-finite loss {loss}"
    del state
    return step_s


def _bench_8b_proxy(on_tpu: bool, devices, kind: str) -> dict:
    """Two-depth layer scan of the true 8B layer geometry; projects MFU
    at n_layers=32 from the per-layer time differential."""
    import optax

    from ray_tpu.models import llama

    if on_tpu:
        base = dataclasses.replace(llama.LLAMA3_8B, max_seq_len=2048,
                                   fused_ops=True)
        batch, seq, warmup, iters = 4, 2048, 2, 6
        depth_pairs = [(2, 6), (2, 4)]  # fallback shrinks HBM footprint
    else:
        base = llama.tiny_config(max_seq_len=256)
        batch, seq, warmup, iters = 2, 256, 1, 2
        depth_pairs = [(1, 2)]
    # SGD: adamw's moment buffers alone would not fit next to 8B-geometry
    # params at depth 6 on a 16 GB chip; optimizer choice does not move
    # the matmul-bound step time materially (method recorded in the row).
    tx = optax.sgd(1e-4)
    last_err = None
    for d_lo, d_hi in depth_pairs:
        try:
            t_lo = _bench_train(dataclasses.replace(base, n_layers=d_lo),
                                batch, seq, warmup, iters, devices, tx)
            t_hi = _bench_train(dataclasses.replace(base, n_layers=d_hi),
                                batch, seq, warmup, iters, devices, tx)
        except Exception as e:  # noqa: BLE001 - OOM at this depth: shrink
            last_err = e
            continue
        per_layer = (t_hi - t_lo) / (d_hi - d_lo)
        full_layers = llama.LLAMA3_8B.n_layers if on_tpu else 4
        t_full = t_lo + (full_layers - d_lo) * per_layer
        tokens_per_s = batch * seq / t_full
        full_cfg = dataclasses.replace(base, n_layers=full_layers)
        mfu = (tokens_per_s * full_cfg.flops_per_token(seq)
               / peak_flops_for(kind) / len(devices))
        return {
            "metric": "train_mfu_llama8b_proxy",
            "value": round(mfu, 4),
            "unit": "mfu",
            "vs_baseline": round(mfu / 0.40, 4),
            "tokens_per_s_per_chip": round(tokens_per_s / len(devices), 1),
            "projected_step_time_s": round(t_full, 4),
            "method": (f"layer-scan: measured depths {d_lo},{d_hi} of 8B "
                       f"geometry (d4096/ff14336/GQA32-8/vocab128k), "
                       f"extrapolated to {full_layers} layers; SGD; full "
                       f"remat; chunked CE"),
            "measured_step_s": {str(d_lo): round(t_lo, 4),
                                str(d_hi): round(t_hi, 4)},
            "batch": batch, "seq": seq,
        }
    return {"metric": "train_mfu_llama8b_proxy", "value": 0.0,
            "unit": "mfu", "vs_baseline": 0.0,
            "error": f"all depth pairs failed: {last_err!r:.300}"}


def _bench_decode(on_tpu: bool, quantize: str = None,
                  paged: bool = False) -> dict:
    """Steady-state decode throughput of the native LLM engine
    (``quantize="int8"`` measures the weight-only-quantized engine on
    the identical workload — the decode path is weight-bandwidth bound,
    so halving the weight bytes is the headline lever; ``paged=True``
    routes decode attention through the paged block-table kernel,
    which on TPU streams only the pages covering each sequence's valid
    rows instead of the whole cache extent — off-TPU the row runs the
    gather reference and exists for cross-round comparability, not
    speed)."""
    import threading

    import numpy as np

    from ray_tpu.models import llama
    from ray_tpu.serve.llm import LLMEngine

    if on_tpu:
        cfg = dataclasses.replace(llama.LLAMA3_1B, max_seq_len=512,
                                  use_decode_kernel=True)
        max_batch, new_tokens, seconds = 8, 48, 8.0
    else:
        cfg = llama.tiny_config(max_seq_len=256)
        max_batch, new_tokens, seconds = 4, 8, 2.0
    # decode_chunk=8: one host sync per 8 tokens — through the remote-TPU
    # tunnel per-token sync alone caps throughput at ~13 steps/s.
    engine = LLMEngine(cfg, max_batch=max_batch, max_len=256,
                       prompt_buckets=[32], decode_chunk=8,
                       quantize=quantize, paged_decode=paged)
    rng = np.random.default_rng(0)

    hi = min(1000, cfg.vocab_size - 1)

    def prompt():
        return [int(t) for t in rng.integers(1, hi, 16)]

    engine.generate(prompt(), max_new_tokens=2)  # compile prefill+decode
    stop_at = time.perf_counter() + seconds
    counts = [0] * max_batch
    client_errors = []

    def client(i):
        try:
            while time.perf_counter() < stop_at:
                out = engine.generate(prompt(), max_new_tokens=new_tokens,
                                      timeout=300)
                counts[i] += len(out["token_ids"])
        except Exception as e:  # noqa: BLE001 — recorded, never silent
            client_errors.append(repr(e)[:200])

    t0 = time.perf_counter()
    threads = [threading.Thread(target=client, args=(i,))
               for i in range(max_batch)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.perf_counter() - t0
    engine.close()
    if client_errors and not sum(counts):
        raise RuntimeError(f"all decode clients failed: {client_errors[0]}")
    tps = sum(counts) / elapsed
    metric = ("llm_decode_tokens_per_s_paged" if paged
              else "llm_decode_tokens_per_s_int8" if quantize == "int8"
              else "llm_decode_tokens_per_s")
    row = {"metric": metric, "value": round(tps, 1),
           "unit": "tokens/s",
           "config": "llama3-1b" if on_tpu else "tiny-cpu",
           "max_batch": max_batch}
    if quantize:
        row["quantize"] = quantize
    if paged:
        row["paged_decode"] = True
    if client_errors:
        # Dead clients deflate throughput: a plausible-but-wrong number
        # must carry the evidence (module invariant).
        row["client_errors"] = len(client_errors)
        row["client_error_sample"] = client_errors[0]
    return row


def _bench_engine(on_tpu: bool) -> dict:
    """Engine suite: decode throughput + TTFT + prefix-cache hit rate
    measured directly on the serve/engine subsystem (no serve stack).

    Clients share a common prompt prefix, so slot recycling exercises
    the prefix cache the way a chat workload (shared system prompt)
    would; TTFT comes from the engine's own metrics (prefill + queue
    wait), not a client-side stopwatch."""
    import threading

    import numpy as np

    from ray_tpu.models import llama
    from ray_tpu.serve.llm import LLMEngine

    if on_tpu:
        cfg = dataclasses.replace(llama.LLAMA3_1B, max_seq_len=512,
                                  use_decode_kernel=True)
        max_batch, new_tokens, seconds = 8, 48, 8.0
    else:
        cfg = llama.tiny_config(max_seq_len=256)
        max_batch, new_tokens, seconds = 4, 8, 2.0
    # Build THIS engine under the RTPU_DEBUG_JAX witness: the row
    # records the steady-state compiled-program counts (program creep =
    # silent retraces = the slowest possible regression). The WARM-UP
    # also runs under jax.transfer_guard("disallow") to prove the tick
    # is free of implicit transfers on this backend's real path — but
    # the guard (and the flag) comes OFF before the timed region, so a
    # guard-unclean path degrades to guard_clean:false instead of
    # destroying the headline row, and the timed numbers stay
    # comparable with pre-witness rounds. Program counting lives in the
    # wrappers installed at construction and keeps working after the
    # env restore; the other bench engines stay unwitnessed.
    prev_env = {k: os.environ.get(k)
                for k in ("RTPU_DEBUG_JAX",
                          "RTPU_DEBUG_JAX_TRANSFER_GUARD")}

    def restore_env():
        for k, v in prev_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    os.environ["RTPU_DEBUG_JAX"] = "1"
    os.environ["RTPU_DEBUG_JAX_TRANSFER_GUARD"] = "disallow"
    engine = None
    guard_clean = True
    try:
        engine = LLMEngine(cfg, max_batch=max_batch, max_len=256,
                           prompt_buckets=[32], decode_chunk=8,
                           prefix_block=8, name="bench-engine")
        rng = np.random.default_rng(0)
        hi = min(1000, cfg.vocab_size - 1)
        shared = [int(t) for t in rng.integers(1, hi, 16)]  # prefix

        def prompt():
            return shared + [int(t) for t in rng.integers(1, hi, 8)]

        try:
            engine.generate(prompt(), max_new_tokens=2)  # guarded warm
        except Exception as e:  # noqa: BLE001 — guard violation: an
            # implicit transfer on THIS backend's tick path. Record it,
            # drop the guard, and re-warm so the row still measures.
            guard_clean = False
            guard_error = repr(e)[:200]
            os.environ.pop("RTPU_DEBUG_JAX_TRANSFER_GUARD", None)
            engine.generate(prompt(), max_new_tokens=2)
        restore_env()
        stop_at = time.perf_counter() + seconds
        counts = [0] * max_batch
        client_errors = []

        def client(i):
            try:
                while time.perf_counter() < stop_at:
                    out = engine.generate(prompt(),
                                          max_new_tokens=new_tokens,
                                          timeout=300)
                    counts[i] += len(out["token_ids"])
            except Exception as e:  # noqa: BLE001 — recorded below
                client_errors.append(repr(e)[:200])

        t0 = time.perf_counter()
        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(max_batch)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        elapsed = time.perf_counter() - t0
        stats = engine.stats()
    finally:
        # Restore on EVERY path (idempotent): a leaked flag would
        # witness-wrap (and transfer-guard) the spec engines built
        # later in this process.
        restore_env()
        if engine is not None:
            engine.close()
    if client_errors and not sum(counts):
        raise RuntimeError(f"all engine clients failed: {client_errors[0]}")
    row = {"metric": "llm_engine",
           "llm_decode_tokens_per_s": round(sum(counts) / elapsed, 1),
           "ttft_ms": stats["ttft_ms_p50"],
           "tpot_ms": stats["tpot_ms_p50"],
           "prefix_hit_rate": stats["prefix_hit_rate"],
           "decode_host_syncs": stats["decode_host_syncs"],
           # Recompile-witness program counts: steady-state should be
           # decode_chunk=1, prefill=1 (one bucket here) — growth
           # round-over-round means something started retracing.
           "compiled_programs": stats.get("compiled_programs"),
           # Was the GUARDED warm-up tick free of implicit transfers on
           # this backend's real path? (The timed region runs
           # unguarded either way.)
           "transfer_guard_clean": guard_clean,
           "config": "llama3-1b" if on_tpu else "tiny-cpu",
           "max_batch": max_batch, "decode_chunk": 8}
    if not guard_clean:
        row["transfer_guard_error"] = guard_error
    if client_errors:
        row["client_errors"] = len(client_errors)
        row["client_error_sample"] = client_errors[0]
    return row


def _bench_engine_spec(on_tpu: bool) -> list:
    """Speculative-decoding suite: a repetitive/code-like workload —
    where prompt-lookup drafting bites — measured back-to-back with
    speculation ON and OFF on otherwise identical engines, so the
    speedup is a measured ratio from one process, not an assertion.

    The repetitive prompt drives the generation into the repetition
    loops real serving sees in code edits / templated output; greedy
    outputs are token-identical between the two runs (the engine's
    equivalence invariant), so both rows count the same tokens."""
    import threading

    from ray_tpu.models import llama
    from ray_tpu.serve.llm import LLMEngine

    if on_tpu:
        cfg = dataclasses.replace(llama.LLAMA3_1B, max_seq_len=512,
                                  use_decode_kernel=True)
        max_batch, new_tokens, seconds = 8, 160, 8.0
    else:
        cfg = llama.tiny_config(max_seq_len=256)
        max_batch, new_tokens, seconds = 4, 200, 4.0
    # A constant-token prompt is the distilled repetitive workload: the
    # generation locks into repetition loops the drafter tracks.
    prompt = [16] * 24
    spec_kw = dict(spec_draft_len=12, spec_chunk=2, spec_ngram_max=8)

    def run(spec: bool) -> dict:
        engine = LLMEngine(cfg, max_batch=max_batch, max_len=256,
                           prompt_buckets=[32], decode_chunk=8,
                           name=f"bench-spec-{'on' if spec else 'off'}",
                           **(spec_kw if spec else {}))
        for _ in range(2):  # compile prefill+decode(+verify), warm ctrl
            engine.generate(prompt, max_new_tokens=120)
        stop_at = time.perf_counter() + seconds
        counts = [0] * max_batch
        errors: list = []

        def client(i):
            try:
                while time.perf_counter() < stop_at:
                    out = engine.generate(prompt,
                                          max_new_tokens=new_tokens,
                                          timeout=300)
                    counts[i] += len(out["token_ids"])
            except Exception as e:  # noqa: BLE001 — recorded below
                errors.append(repr(e)[:200])

        t0 = time.perf_counter()
        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(max_batch)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        elapsed = time.perf_counter() - t0
        stats = engine.stats()
        engine.close()
        if errors and not sum(counts):
            raise RuntimeError(f"all spec-bench clients failed: "
                               f"{errors[0]}")
        out = {"tokens_per_s": round(sum(counts) / elapsed, 1),
               "stats": stats, "errors": errors}
        return out

    on = run(spec=True)
    off = run(spec=False)
    common = {"workload": "repetitive", "prompt_len": len(prompt),
              "max_batch": max_batch, "decode_chunk": 8,
              "config": "llama3-1b" if on_tpu else "tiny-cpu"}
    row_on = {"metric": "llm_engine_spec",
              "llm_decode_tokens_per_s": on["tokens_per_s"],
              "llm_spec_accept_rate": on["stats"]["spec_accept_rate"],
              "spec_drafted": on["stats"]["spec_drafted"],
              "spec_accepted": on["stats"]["spec_accepted"],
              "decode_utilization": on["stats"]["decode_utilization"],
              "spec_speedup": round(
                  on["tokens_per_s"] / off["tokens_per_s"], 2)
              if off["tokens_per_s"] else None,
              **spec_kw, **common}
    row_off = {"metric": "llm_engine_spec_off",
               "llm_decode_tokens_per_s": off["tokens_per_s"],
               "decode_utilization": off["stats"]["decode_utilization"],
               **common}
    for row, r in ((row_on, on), (row_off, off)):
        if r["errors"]:
            row["client_errors"] = len(r["errors"])
            row["client_error_sample"] = r["errors"][0]
    return [row_on, row_off]


def _bench_engine_mixed(on_tpu: bool) -> list:
    """Mixed long-prompt + long-decode sweep: streaming decode clients'
    p99 TPOT while long prompts keep arriving, chunked prefill ON vs
    OFF on otherwise identical engines.

    Unchunked, every long-prompt admission prefills its whole bucket in
    one dispatch between the roster's decode chunks — the in-flight
    streams stall for the full prefill and the stall lands in their
    inter-token p99. Chunked, the same prompt materializes
    ``prefill_chunk`` tokens per tick, bounding any single stall (this
    is also what keeps the PR 9 SLO admission gate from shedding on a
    single long prompt). Greedy outputs are identical in both phases —
    only the interleaving changes."""
    import threading

    import numpy as np

    from ray_tpu.models import llama
    from ray_tpu.serve.llm import LLMEngine

    if on_tpu:
        cfg = dataclasses.replace(llama.LLAMA3_1B, max_seq_len=512,
                                  use_decode_kernel=True)
        seconds = 8.0
    else:
        cfg = llama.tiny_config(max_seq_len=256)
        seconds = 4.0
    long_prompt_len, decode_new = 200, 48
    rng = np.random.default_rng(3)
    hi = min(1000, cfg.vocab_size - 1)
    long_prompts = [[int(t) for t in rng.integers(1, hi,
                                                  long_prompt_len)]
                    for _ in range(4)]
    decode_prompts = [[int(t) for t in rng.integers(1, hi, 16)]
                      for _ in range(2)]

    def run(prefill_chunk: int) -> dict:
        engine = LLMEngine(cfg, max_batch=4, max_len=256,
                           prompt_buckets=[32, 224], decode_chunk=8,
                           prefill_chunk=prefill_chunk,
                           name=f"bench-mixed-{prefill_chunk}")
        # Warm every program: both prefill buckets + decode.
        engine.generate(long_prompts[0], max_new_tokens=2)
        engine.generate(decode_prompts[0], max_new_tokens=2)
        stop_at = time.perf_counter() + seconds
        gaps: list = []
        gaps_lock = threading.Lock()
        errors: list = []
        decoded = [0, 0]  # per-thread counts (no shared-counter race)

        def decode_client(i):
            try:
                while time.perf_counter() < stop_at:
                    last = None
                    local = []
                    for _ in engine.generate_stream(
                            decode_prompts[i], max_new_tokens=decode_new,
                            timeout=300):
                        now = time.perf_counter()
                        if last is not None:
                            local.append(now - last)  # TPOT, not TTFT
                        last = now
                        decoded[i] += 1
                    with gaps_lock:
                        gaps.extend(local)
            except Exception as e:  # noqa: BLE001 — recorded below
                errors.append(repr(e)[:200])

        def prompt_client(i):
            try:
                while time.perf_counter() < stop_at:
                    engine.generate(long_prompts[i % len(long_prompts)],
                                    max_new_tokens=2, timeout=300)
            except Exception as e:  # noqa: BLE001 — recorded below
                errors.append(repr(e)[:200])

        threads = ([threading.Thread(target=decode_client, args=(i,))
                    for i in range(2)]
                   + [threading.Thread(target=prompt_client, args=(i,))
                      for i in range(2)])
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        engine.close()
        if errors and not gaps:
            raise RuntimeError(f"mixed-bench clients failed: {errors[0]}")
        gaps.sort()
        p = {q: round(gaps[min(int(q / 100 * len(gaps)),
                               len(gaps) - 1)] * 1e3, 3)
             for q in (50, 99)} if gaps else {50: None, 99: None}
        return {"p50_tpot_ms": p[50], "p99_tpot_ms": p[99],
                "decode_tokens": sum(decoded),
                "tpot_samples": len(gaps), "errors": errors}

    chunk = 32
    on = run(prefill_chunk=chunk)
    off = run(prefill_chunk=0)
    common = {"workload": "mixed-long-prompt",
              "long_prompt_len": long_prompt_len,
              "decode_new_tokens": decode_new, "max_batch": 4,
              "config": "llama3-1b" if on_tpu else "tiny-cpu"}
    rows = []
    for tag, r, pc in (("chunked", on, chunk), ("unchunked", off, 0)):
        row = {"metric": f"llm_engine_mixed_{tag}",
               "prefill_chunk": pc, **{k: v for k, v in r.items()
                                       if k != "errors"}, **common}
        if r["errors"]:
            row["client_errors"] = len(r["errors"])
            row["client_error_sample"] = r["errors"][0]
        rows.append(row)
    if on["p99_tpot_ms"] and off["p99_tpot_ms"]:
        # >1 means chunked prefill flattened the decode tail.
        rows[0]["p99_tpot_flatness_vs_unchunked"] = round(
            off["p99_tpot_ms"] / on["p99_tpot_ms"], 2)
    return rows


def engine_child_main() -> None:
    """Standalone engine suite (``bench.py --engine``): engine row, the
    paged-decode row, the speculative-decoding on/off pair, and the
    mixed long-prompt sweep (chunked prefill on/off), one JSON row
    each."""
    _pin_platform()
    import jax

    on_tpu = jax.devices()[0].platform == "tpu"
    print(json.dumps(_bench_engine(on_tpu)), flush=True)
    print(json.dumps(_bench_decode(on_tpu, paged=True)), flush=True)
    for row in _bench_engine_spec(on_tpu):
        print(json.dumps(row), flush=True)
    for row in _bench_engine_mixed(on_tpu):
        print(json.dumps(row), flush=True)


# --------------------------------------------------------------------------
# ops microbench suite (--ops): per-kernel fused-vs-unfused + int8 matmul
# --------------------------------------------------------------------------

def _timed_chain(fn, state, iters: int, warmup: int = 3):
    """Seconds per call for a shape-preserving jitted fn, chained
    state -> state so XLA cannot hoist the work; one host fetch per
    timed region (the only reliable barrier through the TPU tunnel)."""
    import jax

    for _ in range(warmup):
        state = fn(state)
    float(jax.tree.leaves(state)[0].ravel()[0])  # drain warmup work
    t0 = time.perf_counter()
    for _ in range(iters):
        state = fn(state)
    leaves = jax.tree.leaves(jax.tree.map(lambda a: a.ravel()[0], state))
    float(leaves[0])
    return (time.perf_counter() - t0) / iters


def _bench_ops(on_tpu: bool) -> list:
    """Per-kernel microbenches: fused vs unfused step time for the
    model-path glue, and the decode matmul's weight GB/s at the
    working dtype vs
    weight-only int8. Small and self-contained so a kernel regression
    shows up in every BENCH round."""
    import jax
    import jax.numpy as jnp

    from ray_tpu import ops

    if on_tpu:
        b, s, d, f, h, kh, hd = 8, 2048, 2048, 8192, 32, 8, 64
        fm, iters, dt = 8192, 30, jnp.bfloat16
    else:
        b, s, d, f, h, kh, hd = 2, 128, 64, 128, 4, 2, 16
        fm, iters, dt = 512, 10, jnp.float32
    config = "llama1b-shapes" if on_tpu else "tiny-cpu"
    key = jax.random.PRNGKey(0)
    rows = []

    def row(op, fused_fn, plain_fn, state, shape):
        t_plain = _timed_chain(jax.jit(plain_fn), state, iters)
        t_fused = _timed_chain(jax.jit(fused_fn), state, iters)
        rows.append({
            "metric": "ops_microbench", "op": op,
            "fused_us": round(t_fused * 1e6, 1),
            "unfused_us": round(t_plain * 1e6, 1),
            "speedup": round(t_plain / t_fused, 3) if t_fused else None,
            "shape": shape, "config": config})

    # Fused-vs-unfused is only a measurement where the fused path IS a
    # kernel: off-TPU the dispatchers fall back to the very references
    # the "unfused" lambdas call, so the ratio would be two timings of
    # the same function — round-over-round noise dressed as a signal.
    if on_tpu:
        x = jax.random.normal(key, (b, s, d), dt)
        scale = jax.random.normal(jax.random.fold_in(key, 1), (d,),
                                  jnp.float32) * 0.1
        row("rms_norm",
            lambda x: ops.fused_rms_norm(x, scale),
            lambda x: ops.rms_norm(x, scale),
            x, [b, s, d])

        q = jax.random.normal(jax.random.fold_in(key, 2), (b, s, h, hd),
                              dt)
        k = jax.random.normal(jax.random.fold_in(key, 3), (b, s, kh, hd),
                              dt)
        pos = jnp.broadcast_to(jnp.arange(s), (b, s))
        row("rope_qk",
            lambda qk: ops.fused_qk_rope(qk[0], qk[1], pos),
            lambda qk: (ops.apply_rope(qk[0], pos),
                        ops.apply_rope(qk[1], pos)),
            (q, k), [b, s, h, hd])

        gate = jax.random.normal(jax.random.fold_in(key, 4), (b, s, f),
                                 dt)
        up = jax.random.normal(jax.random.fold_in(key, 5), (b, s, f), dt)
        row("swiglu",
            lambda g: ops.fused_swiglu(g, up),
            lambda g: (jax.nn.silu(g) * up).astype(g.dtype),
            gate, [b, s, f])

    # Decode-shaped matmul: tiny activation against a big square weight
    # — pure weight streaming, the thing int8 halves. GB/s counts the
    # WEIGHT bytes actually read per step. The weights ride the chained
    # STATE (jit arguments), never a closure: a closed-over int8 weight
    # gets constant-folded to full width at trace time and the "int8"
    # timing silently streams full-precision bytes (verified in HLO).
    w = jax.random.normal(jax.random.fold_in(key, 6), (fm, fm), dt)
    wq = jnp.clip(jnp.round(w.astype(jnp.float32) * 127), -127,
                  127).astype(jnp.int8)
    wscale = jnp.full((fm,), 1.0 / 127, jnp.float32)
    xa = jax.random.normal(jax.random.fold_in(key, 7), (8, fm), dt)
    t_base = _timed_chain(
        jax.jit(lambda s: ((s[0] @ s[1]).astype(dt), s[1])),
        (xa, w), iters)
    t_int8 = _timed_chain(
        jax.jit(lambda s: (((s[0] @ s[1].astype(s[0].dtype))
                            * s[2]).astype(dt), s[1], s[2])),
        (xa, wq, wscale), iters)
    rows.append({
        "metric": "decode_matmul_gbps",
        # "baseline" = the model's working dtype (bf16 on TPU, f32 on
        # CPU) — named by the dtype field, not mislabelled f32.
        "baseline_gbps": round(fm * fm * w.dtype.itemsize / t_base / 1e9,
                               2),
        "int8_gbps": round(fm * fm * 1 / t_int8 / 1e9, 2),
        "baseline_dtype": jnp.dtype(dt).name,
        "speedup": round(t_base / t_int8, 3) if t_int8 else None,
        "weight_shape": [fm, fm], "batch": 8, "config": config})
    return rows


def ops_main() -> int:
    """Standalone ``--ops``: per-kernel rows + one merged tail line."""
    _pin_platform()
    import jax

    on_tpu = jax.devices()[0].platform == "tpu"
    rows = _bench_ops(on_tpu)
    for r in rows:
        print(json.dumps(r), flush=True)
    print(json.dumps(_merge_ops_rows(rows)))
    return 0


def _merge_ops_rows(rows: list) -> dict:
    merged = {"metric": "ops"}
    for r in rows:
        if r.get("metric") == "ops_microbench" and "error" not in r:
            merged[f"ops_fused_{r['op']}_speedup"] = r.get("speedup")
        elif r.get("metric") == "decode_matmul_gbps" and "error" not in r:
            merged["decode_matmul_baseline_gbps"] = r.get("baseline_gbps")
            merged["decode_matmul_baseline_dtype"] = \
                r.get("baseline_dtype")
            merged["decode_matmul_int8_gbps"] = r.get("int8_gbps")
            merged["decode_matmul_int8_speedup"] = r.get("speedup")
        elif "error" in r:
            merged.setdefault("error", r["error"])
    return merged


def child_main() -> None:
    _pin_platform()
    import jax

    from ray_tpu.models import llama

    devices = jax.devices()
    on_tpu = devices[0].platform == "tpu"
    kind = devices[0].device_kind

    # --- row 1: Llama-1B full-model MFU (round-over-round continuity) ---
    # fused_ops=True: Pallas-fused RMSNorm/rope/SwiGLU on TPU
    # (ops/fused.py; off-TPU the flag falls back to the references, so
    # the CPU row is unaffected). Equivalence vs the unfused path is
    # tier-1-tested (tests/test_fused_ops.py).
    if on_tpu:
        cfg = dataclasses.replace(llama.LLAMA3_1B, max_seq_len=2048,
                                  fused_ops=True)
        batch, seq, warmup, iters = 8, 2048, 2, 10
    else:
        cfg = llama.tiny_config(max_seq_len=256)
        batch, seq, warmup, iters = 4, 256, 1, 3
    step_s = _bench_train(cfg, batch, seq, warmup, iters, devices)
    tokens_per_s_chip = batch * seq / step_s / len(devices)
    mfu1b = tokens_per_s_chip * cfg.flops_per_token(seq) / peak_flops_for(kind)
    row_1b = {
        "metric": "train_mfu_llama1b",
        "value": round(mfu1b, 4),
        "unit": "mfu",
        "vs_baseline": round(mfu1b / 0.40, 4),
        "tokens_per_s_per_chip": round(tokens_per_s_chip, 1),
        "step_time_s": round(step_s, 4),
        "device": kind,
        "n_chips": len(devices),
        "config": "llama3-1b" if on_tpu else "tiny-cpu",
        "fused_ops": bool(cfg.fused_ops),
        "batch": batch, "seq": seq,
    }
    print(json.dumps(row_1b), flush=True)

    # --- row 2: 8B-class projected MFU (north star) ---------------------
    try:
        row_8b = _bench_8b_proxy(on_tpu, devices, kind)
    except Exception as e:  # noqa: BLE001
        row_8b = {"metric": "train_mfu_llama8b_proxy", "value": 0.0,
                  "unit": "mfu", "vs_baseline": 0.0,
                  "error": repr(e)[:300]}
    print(json.dumps(row_8b), flush=True)

    # --- row 3: engine decode throughput on the chip --------------------
    try:
        row_dec = _bench_decode(on_tpu)
    except Exception as e:  # noqa: BLE001
        row_dec = {"metric": "llm_decode_tokens_per_s", "value": 0.0,
                   "unit": "tokens/s", "error": repr(e)[:300]}
    print(json.dumps(row_dec), flush=True)

    # --- row 3b: same decode workload, weight-only int8 engine ----------
    try:
        row_q = _bench_decode(on_tpu, quantize="int8")
        if row_dec.get("value") and row_q.get("value"):
            row_q["speedup_vs_f32"] = round(
                row_q["value"] / row_dec["value"], 3)
    except Exception as e:  # noqa: BLE001
        row_q = {"metric": "llm_decode_tokens_per_s_int8", "value": 0.0,
                 "unit": "tokens/s", "error": repr(e)[:300]}
    print(json.dumps(row_q), flush=True)

    # --- row 3c: same decode workload, paged block-table kernel --------
    try:
        row_p = _bench_decode(on_tpu, paged=True)
        if row_dec.get("value") and row_p.get("value"):
            row_p["speedup_vs_unpaged"] = round(
                row_p["value"] / row_dec["value"], 3)
    except Exception as e:  # noqa: BLE001
        row_p = {"metric": "llm_decode_tokens_per_s_paged", "value": 0.0,
                 "unit": "tokens/s", "error": repr(e)[:300]}
    print(json.dumps(row_p), flush=True)

    # --- row 4: engine suite (decode + TTFT + prefix-cache) -------------
    try:
        row_eng = _bench_engine(on_tpu)
    except Exception as e:  # noqa: BLE001
        row_eng = {"metric": "llm_engine", "error": repr(e)[:300]}
    print(json.dumps(row_eng), flush=True)

    # --- rows 5+6: speculative decoding on/off (repetitive workload) ----
    try:
        spec_rows = _bench_engine_spec(on_tpu)
    except Exception as e:  # noqa: BLE001
        spec_rows = [{"metric": "llm_engine_spec", "error": repr(e)[:300]}]
    for r in spec_rows:
        print(json.dumps(r), flush=True)

    # --- rows 6b: mixed long-prompt sweep, chunked prefill on/off -------
    try:
        mixed_rows = _bench_engine_mixed(on_tpu)
    except Exception as e:  # noqa: BLE001
        mixed_rows = [{"metric": "llm_engine_mixed_chunked",
                       "error": repr(e)[:300]}]
    for r in mixed_rows:
        print(json.dumps(r), flush=True)

    # --- rows 7+: per-kernel ops microbench (fused glue + int8 matmul) --
    try:
        ops_rows = _bench_ops(on_tpu)
    except Exception as e:  # noqa: BLE001
        ops_rows = [{"metric": "ops_microbench", "error": repr(e)[:300]}]
    for r in ops_rows:
        print(json.dumps(r), flush=True)


def serve_child_main() -> None:
    """Full-stack serve bench; runs on CPU (the TPU child owns the chip)."""
    from ray_tpu.serve.benchmark import run_benchmark

    rows = run_benchmark(seconds=6.0, concurrency=4)
    print(json.dumps({"metric": "serve_llm", **rows}), flush=True)


# --------------------------------------------------------------------------
# routed-serve sweep (--serve): routing policies under skewed-prefix load
# --------------------------------------------------------------------------

def serve_routed_child_main() -> int:
    """One full routing-policy pass: ONE cluster, a sequence of
    measurement phases (policy list from RTPU_SERVE_SWEEP_ORDER,
    default alternating random/scored x3 then one pow2 phase) —
    adjacent phases share the host-noise window, and alternating the
    two headline policies several times means a noise burst corrupts
    at most one phase per side; the parent takes per-policy medians.
    Each phase deploys a FRESH 2-replica tiny-cpu engine deployment
    (fresh KV: no residency carry-over between policies), drives
    closed-loop skewed-prefix traffic, tears the deployment down, and
    prints one JSON row.

    Workload: 8 prefix groups of 224 tokens (14 cache blocks) + 8
    fresh suffix tokens, mildly skewed popularity. The full group set
    (112 blocks) overcommits one replica's 80-block KV pool — blind
    routing churns eviction — while a 4-group affinity partition (56
    blocks) stays resident. A prefix HIT prefills only the suffix
    (16-bucket); a miss pays the full 232-bucket prefill. Decode is
    held to ONE 1-step dispatch (prefill itself yields the first
    token) so the policy-neutral decode floor doesn't drown the
    prefill asymmetry on 2 CPU cores. Streams every request to
    measure true TTFT."""
    import threading

    import numpy as np

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.core.config import GLOBAL_CONFIG
    from ray_tpu.serve._private.controller import CONTROLLER_NAME
    from ray_tpu.serve.llm import build_llm_deployment

    order = [p.strip() for p in os.environ.get(
        "RTPU_SERVE_SWEEP_ORDER",
        "random,scored,random,scored,random,scored,pow2").split(",")
        if p.strip()]
    seconds, n_replicas, concurrency = 8.0, 2, 6
    prefix_len, suffix_len, new_tokens = 224, 8, 2

    # Tracing ON for the whole sweep (both policies pay the same cost):
    # the TTFT-breakdown keys (queue/route/prefill) are derived from the
    # head's span ring, so routing/SLO changes are judged on decomposed
    # TTFT instead of noisy end-to-end medians.
    rt = ray_tpu.init(num_cpus=max(8, os.cpu_count() or 8),
                      _system_config={"tracing_enabled": True})
    rng = np.random.default_rng(11)
    groups = [[int(t) for t in rng.integers(1, 200, prefix_len)]
              for _ in range(8)]
    pop = 1.0 / (np.arange(8) + 4.0)
    pop = pop / pop.sum()

    def make_payload(r):
        g = int(r.choice(len(groups), p=pop))
        suffix = [int(t) for t in r.integers(1, 200, suffix_len)]
        return {"prompt_ids": groups[g] + suffix,
                "max_new_tokens": new_tokens}

    for phase_i, policy in enumerate(order):
        GLOBAL_CONFIG.set("serve_router_policy", policy)
        name = f"routed-{phase_i}-{policy}"
        handle = serve.run(build_llm_deployment(
            name=name, num_replicas=n_replicas,
            engine_kwargs={"max_batch": 4, "max_len": 320,
                           "prompt_buckets": [16, 232],
                           "prefix_block": 16, "decode_chunk": 1}),
            name=name)
        # Warm every replica's programs off the measured path with a
        # NEUTRAL prompt (not a group prefix: warmup must not pre-seed
        # affinity for any policy).
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
        replicas = ray_tpu.get(controller.get_replicas.remote(name),
                               timeout=60)
        warm_full = {"prompt_ids": [210] * (prefix_len + suffix_len),
                     "max_new_tokens": new_tokens}
        warm_small = {"prompt_ids": [210] * 12,
                      "max_new_tokens": new_tokens}
        ray_tpu.get([r.handle_request.remote("__call__", (w,), {})
                     for r in replicas for w in (warm_full, warm_small)],
                    timeout=900)
        # Let one snapshot sweep land so scored routing starts informed.
        time.sleep(1.5)

        phase_t0_wall = time.time()
        stop_at = time.perf_counter() + seconds
        ttfts: list = []
        tokens = [0] * concurrency
        reqs = [0] * concurrency
        errs = [0] * concurrency
        last_err: list = [None]
        lock = threading.Lock()

        def client(i: int) -> None:
            r = np.random.default_rng(1000 + i)
            while time.perf_counter() < stop_at:
                # One failed request must not kill the whole closed-loop
                # client: a phase quietly running 5 clients instead of 6
                # would bias exactly the policy comparison the
                # alternating-median design protects.
                try:
                    gen = handle.options("stream", stream=True).remote(
                        make_payload(r))
                    t0 = time.perf_counter()
                    n = 0
                    for _tok in gen:
                        if n == 0:
                            with lock:
                                ttfts.append(
                                    (time.perf_counter() - t0) * 1e3)
                        n += 1
                    tokens[i] += n
                    reqs[i] += 1
                except Exception as e:
                    errs[i] += 1
                    with lock:
                        last_err[0] = repr(e)

        t0 = time.perf_counter()
        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(concurrency)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        elapsed = time.perf_counter() - t0

        stats = ray_tpu.get([r.handle_request.remote("stats", (), {})
                             for r in replicas], timeout=60)
        hits = sum(s["prefix_hits"] for s in stats)
        misses = sum(s["prefix_misses"] for s in stats)
        ttfts.sort()

        # TTFT decomposition from the head's span ring: median duration
        # of this phase's serve.route / engine.queued / engine.prefill
        # spans (spans started during the measurement window only).
        def _span_breakdown() -> dict:
            want = {"serve.route": "ttft_route_ms",
                    "engine.queued": "ttft_queue_ms",
                    "engine.prefill": "ttft_prefill_ms"}
            buckets: dict = {k: [] for k in want.values()}
            try:
                # Driver-side spans (serve.route) buffer locally until
                # the 64-span high-water mark: flush before reading the
                # head ring or the newest routes are always missing.
                from ray_tpu.util import tracing as _tr

                _tr.flush()
                spans = rt.head.retrying_call("trace_tail", 50000,
                                              timeout=10)
            except Exception as e:
                print(f"breakdown span fetch failed: {e!r}",
                      file=sys.stderr, flush=True)
                return {}
            for s in spans:
                key = want.get(s.get("name"))
                if key is None or s.get("end") is None:
                    continue
                if s["start"] < phase_t0_wall:
                    continue
                buckets[key].append((s["end"] - s["start"]) * 1e3)
            out = {}
            for key, vals in buckets.items():
                if vals:
                    vals.sort()
                    out[key] = round(vals[len(vals) // 2], 3)
            return out

        row = {
            "metric": "serve_routed",
            "config": "tiny-cpu-2rep",
            "policy": policy,
            "requests_per_s": round(sum(reqs) / elapsed, 2),
            "tokens_per_s": round(sum(tokens) / elapsed, 2),
            "p50_ttft_ms": round(ttfts[len(ttfts) // 2], 2)
                if ttfts else None,
            "p99_ttft_ms": round(
                ttfts[min(len(ttfts) - 1, int(len(ttfts) * 0.99))], 2)
                if ttfts else None,
            "prefix_hit_rate": round(hits / (hits + misses), 4)
                if hits + misses else 0.0,
            "client_errors": sum(errs),
            "client_last_error": last_err[0],
            "router": handle._router.stats(),
        }
        row.update(_span_breakdown())
        print(json.dumps(row), flush=True)
        # Tear the phase's deployment down so the next policy starts
        # from cold KV on an idle cluster.
        ray_tpu.get(controller.delete.remote(name), timeout=60)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            try:
                if not ray_tpu.get(controller.get_replicas.remote(name),
                                   timeout=10):
                    break
            except Exception:  # rtpu-lint: disable=swallowed-exception
                break  # deployment record gone entirely == torn down
            time.sleep(0.5)
    return 0


def _serve_routed_rows(rounds: int = 1) -> list:
    """Run ``rounds`` sweep children. Each child measures the two
    headline policies (random, scored) as ALTERNATING adjacent phases
    on one cluster plus a trailing pow2 phase, so every phase pair
    shares a host-noise window and a burst corrupts at most one phase
    per side. Odd rounds lead with scored so neither policy always
    gets the freshest cluster. Per policy, every metric reduces by
    MEDIAN across all phases of all rounds — robust to a corrupted
    minority of phases and symmetric across policies. Error rows never
    kill the bench."""
    collected: dict = {}
    errors: dict = {}
    policies = ("random", "pow2", "scored")
    for rnd in range(rounds):
        pair = (["random", "scored"] if rnd % 2 == 0
                else ["scored", "random"])
        order = pair * 3 + ["pow2", "pow2"]
        env = {"JAX_PLATFORMS": "cpu",
               "RTPU_SERVE_SWEEP_ORDER": ",".join(order)}
        try:
            proc = _run(["--serve-routed-child"],
                        SERVE_ROUTED_TIMEOUT_S, env_extra=env)
        except subprocess.TimeoutExpired as te:
            # Phases stream one JSON row each as they finish: salvage
            # what the child measured before the hang instead of
            # discarding minutes of completed phases with it.
            partial = te.stdout or ""
            if isinstance(partial, bytes):
                partial = partial.decode(errors="replace")
            rows = [ln for ln in _json_lines(partial)
                    if ln.get("metric") == "serve_routed"
                    and ln.get("policy")]
            for row in rows:
                collected.setdefault(row["policy"], []).append(row)
            for policy in policies:
                if not any(r["policy"] == policy for r in rows):
                    errors.setdefault(policy, {
                        "metric": "serve_routed", "policy": policy,
                        "error": f"timeout {SERVE_ROUTED_TIMEOUT_S}s"})
            continue
        lines = _json_lines(proc.stdout)
        rows = [ln for ln in lines
                if ln.get("metric") == "serve_routed"
                and ln.get("policy")]
        for row in rows:
            collected.setdefault(row["policy"], []).append(row)
        if proc.returncode != 0 or len(rows) < len(order):
            tail = (proc.stderr or proc.stdout).strip() \
                .splitlines()[-3:]
            for policy in policies:
                if not any(r["policy"] == policy for r in rows):
                    errors.setdefault(policy, {
                        "metric": "serve_routed", "policy": policy,
                        "error": "rc=%d: %s" % (proc.returncode,
                                                " | ".join(tail))})

    def _median(vals: list) -> float:
        vals = sorted(vals)
        n = len(vals)
        mid = vals[n // 2] if n % 2 else (vals[n // 2 - 1]
                                          + vals[n // 2]) / 2
        return round(mid, 4)

    out = []
    for p in policies:
        rows = collected.get(p)
        if not rows:
            if p in errors:
                out.append(errors[p])
            continue
        merged = dict(rows[len(rows) // 2])
        merged["phases"] = len(rows)
        for key in ("requests_per_s", "tokens_per_s", "p50_ttft_ms",
                    "p99_ttft_ms", "prefix_hit_rate", "ttft_queue_ms",
                    "ttft_route_ms", "ttft_prefill_ms"):
            vals = [r[key] for r in rows if r.get(key) is not None]
            if vals:
                merged[key] = _median(vals)
        # Router path counters accumulate over every phase: the scored
        # row must prove the affinity path actually ran.
        merged["router"] = {
            k: sum(r.get("router", {}).get(k, 0) for r in rows)
            for k in ("scored_routes", "pow2_routes",
                      "affinity_routes")}
        out.append(merged)
    return out


def _merge_serve_routed_rows(rows: list) -> dict:
    by = {r.get("policy"): r for r in rows}
    merged = {"metric": "serve_routed"}
    sc = by.get("scored", {})
    if "error" in sc or not sc:
        merged["error"] = sc.get("error", "scored row missing")
    else:
        merged["serve_routed_tokens_per_s"] = sc.get("tokens_per_s")
        merged["serve_routed_p99_ttft_ms"] = sc.get("p99_ttft_ms")
        merged["serve_prefix_affinity_hit_rate"] = sc.get("prefix_hit_rate")
        # Span-derived TTFT decomposition (scored phases): future
        # routing/SLO PRs are judged on the component that moved, not
        # on the noisy end-to-end median alone.
        merged["serve_ttft_queue_ms"] = sc.get("ttft_queue_ms")
        merged["serve_ttft_route_ms"] = sc.get("ttft_route_ms")
        merged["serve_ttft_prefill_ms"] = sc.get("ttft_prefill_ms")
    rnd = by.get("random", {})
    if rnd and "error" not in rnd:
        merged["serve_routed_tokens_per_s_random"] = rnd.get("tokens_per_s")
        merged["serve_routed_p99_ttft_ms_random"] = rnd.get("p99_ttft_ms")
        merged["serve_prefix_hit_rate_random"] = rnd.get("prefix_hit_rate")
        if sc.get("tokens_per_s") and rnd.get("tokens_per_s"):
            merged["serve_routed_speedup_vs_random"] = round(
                sc["tokens_per_s"] / rnd["tokens_per_s"], 3)
    p2 = by.get("pow2", {})
    if p2 and "error" not in p2:
        merged["serve_routed_tokens_per_s_pow2"] = p2.get("tokens_per_s")
        merged["serve_routed_p99_ttft_ms_pow2"] = p2.get("p99_ttft_ms")
    return merged


def serve_routed_main() -> int:
    """Standalone ``--serve``: all three policies + one merged tail line."""
    rows = _serve_routed_rows()
    for r in rows:
        print(json.dumps(r), flush=True)
    print(json.dumps(_merge_serve_routed_rows(rows)))
    return 0 if all("error" not in r for r in rows) else 1


# --------------------------------------------------------------------------
# locality suite (--locality): locality-aware scheduling vs forced-random
# --------------------------------------------------------------------------

def locality_child_main() -> None:
    """One locality-workload measurement on a 4-node in-process cluster:
    blocks are produced pinned round-robin across the nodes, then one
    consumer task per block reads its block. With locality scheduling on
    (RTPU_SCHEDULER_LOCALITY_ENABLED=1, the default) consumers land on
    their block's holder node and pull nothing; the ``--random`` child
    (flag off + SPREAD placement) is the forced-random-placement
    baseline whose consumers pull their input over the simulated DCN.
    Prints one JSON row."""
    _pin_platform()
    mode = "random" if "--random" in sys.argv else "locality"
    import ray_tpu as rt
    from ray_tpu.core.runtime_context import require_runtime
    from ray_tpu.util import metrics as _m
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy)

    rt.init(num_cpus=2)
    runtime = require_runtime()
    extra = [runtime.add_node(num_cpus=2) for _ in range(3)]
    node_ids = [runtime._nodes[0].node_id] + [n.node_id for n in extra]

    n_blocks = 24
    block_bytes = 4 << 20

    @rt.remote
    def produce(i: int, nbytes: int):
        import numpy as _np

        return _np.full(nbytes, i % 251, dtype=_np.uint8)

    @rt.remote
    def consume(arr) -> int:
        time.sleep(0.1)  # stand-in compute: keeps one task per lease
        return int(arr[0]) + len(arr)

    blocks = [
        produce.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(
                node_id=node_ids[i % len(node_ids)])
        ).remote(i, block_bytes)
        for i in range(n_blocks)]
    ready, _ = rt.wait(blocks, num_returns=n_blocks, timeout=180)
    assert len(ready) == n_blocks, "block production timed out"

    def pull_totals() -> int:
        pulled = 0
        for n in runtime.nodes():
            try:
                st = runtime._pool.get(n["address"]).call(
                    "pull_stats", timeout=5)
                pulled += int(st.get("bytes_pulled", 0))
            except Exception:
                pass
        return pulled

    opts = {"scheduling_strategy": "SPREAD"} if mode == "random" else {}
    h0 = _m.SCHEDULER_LOCALITY_HITS.get()
    m0 = _m.SCHEDULER_LOCALITY_MISSES.get()
    p0 = pull_totals()
    t0 = time.perf_counter()
    futs = [consume.options(**opts).remote(ref) for ref in blocks]
    out = rt.get(futs, timeout=300)
    wall_s = time.perf_counter() - t0
    assert len(out) == n_blocks
    pulled = pull_totals() - p0
    hits = _m.SCHEDULER_LOCALITY_HITS.get() - h0
    misses = _m.SCHEDULER_LOCALITY_MISSES.get() - m0
    row = {
        "metric": "locality_scheduling", "mode": mode,
        "locality_hit_rate": round(hits / max(1, hits + misses), 3),
        "object_bytes_pulled_per_task": round(pulled / n_blocks, 1),
        "bytes_pulled_total": pulled,
        "locality_hits": hits, "locality_misses": misses,
        "n_tasks": n_blocks, "block_bytes": block_bytes,
        "nodes": len(node_ids), "wall_s": round(wall_s, 2)}
    print(json.dumps(row), flush=True)
    rt.shutdown()


def _locality_suite_rows() -> list:
    """Run both locality children; returns their rows (error rows on
    failure — the suite must never take down the whole bench)."""
    rows = []
    for mode in ("locality", "random"):
        args = ["--locality-child"] + (["--random"] if mode == "random"
                                       else [])
        env = {"JAX_PLATFORMS": "cpu",
               "RTPU_SCHEDULER_LOCALITY_ENABLED":
                   "1" if mode == "locality" else "0"}
        try:
            proc = _run(args, LOCALITY_TIMEOUT_S, env_extra=env)
        except subprocess.TimeoutExpired:
            rows.append({"metric": "locality_scheduling", "mode": mode,
                         "error": f"timeout {LOCALITY_TIMEOUT_S}s"})
            continue
        lines = _json_lines(proc.stdout)
        if proc.returncode == 0 and lines:
            rows.append(lines[-1])
        else:
            tail = (proc.stderr or proc.stdout).strip().splitlines()[-3:]
            rows.append({"metric": "locality_scheduling", "mode": mode,
                         "error": "rc=%d: %s" % (proc.returncode,
                                                 " | ".join(tail))})
    return rows


def locality_main() -> int:
    """Standalone ``--locality``: both modes + one merged tail line."""
    rows = _locality_suite_rows()
    for r in rows:
        print(json.dumps(r), flush=True)
    print(json.dumps(_merge_locality_rows(rows)))
    return 0 if all("error" not in r for r in rows) else 1


def _merge_locality_rows(rows: list) -> dict:
    by = {r.get("mode"): r for r in rows}
    loc, rnd = by.get("locality", {}), by.get("random", {})
    merged = {"metric": "locality_scheduling"}
    if "error" in loc:
        merged["error"] = loc["error"]
    else:
        merged["locality_hit_rate"] = loc.get("locality_hit_rate")
        merged["object_bytes_pulled_per_task"] = \
            loc.get("object_bytes_pulled_per_task")
    if "error" not in rnd:
        merged["object_bytes_pulled_per_task_random"] = \
            rnd.get("object_bytes_pulled_per_task")
    return merged


# --------------------------------------------------------------------------
# dataplane suite (--dataplane): multi-writer store + pull + actor args
# --------------------------------------------------------------------------

_DP_STORE = "/rtpu_bench_dp"
_DP_OBJ = 8 << 20
_DP_SECONDS = 3.0


def _dp_writer(idx: int, barrier, q) -> None:
    """One put+delete writer process over the shared bench store (spawned
    via multiprocessing; must be module-level for pickling)."""
    from ray_tpu.core.ids import ObjectID
    from ray_tpu.core.shm_store import ShmStore

    store = ShmStore.open(_DP_STORE)
    payload = bytearray(_DP_OBJ)

    def oid(i):
        return ObjectID(bytes([idx]) + i.to_bytes(8, "little") + b"\0" * 19)

    for i in range(2):  # warm the affine block (first-touch faults)
        store.put_bytes(oid(1000000 + i), payload)
        store.delete(oid(1000000 + i))
    barrier.wait(timeout=60)
    n = 0
    t0 = time.perf_counter()
    stop = t0 + _DP_SECONDS
    while time.perf_counter() < stop:
        store.put_bytes(oid(n), payload)
        store.delete(oid(n))
        n += 1
    q.put((n, time.perf_counter() - t0))


def _dp_put_gbps(k: int) -> float:
    """Aggregate put bandwidth of k concurrent writer PROCESSES (each in
    its own interpreter and page tables — the real multi-client shape)."""
    import multiprocessing as mp

    from ray_tpu.core.shm_store import ShmStore

    store = ShmStore.create(_DP_STORE, 768 << 20, prefault=False)
    try:
        ctx = mp.get_context("spawn")
        q = ctx.Queue()
        barrier = ctx.Barrier(k)
        procs = [ctx.Process(target=_dp_writer, args=(i, barrier, q))
                 for i in range(k)]
        for p in procs:
            p.start()
        res = [q.get(timeout=120) for _ in range(k)]
        for p in procs:
            p.join(timeout=30)
        return sum(n * _DP_OBJ / dt for n, dt in res) / 1e9
    finally:
        store.close()


def dataplane_child_main() -> None:
    """Store put scaling, then a 2-node cluster for pull bandwidth and
    n x n actor calls with array args. One JSON row per metric."""
    _pin_platform()
    rows = []

    single = _dp_put_gbps(1)
    multi = _dp_put_gbps(4)
    ratio = round(multi / single, 3) if single else None
    rows.append({"metric": "single_put_gbps", "value": round(single, 2),
                 "unit": "GB/s", "object_mib": _DP_OBJ >> 20, "writers": 1})
    rows.append({"metric": "multi_put_gbps", "value": round(multi, 2),
                 "unit": "GB/s", "object_mib": _DP_OBJ >> 20, "writers": 4})
    rows.append({"metric": "put_scaling_ratio", "value": ratio,
                 "unit": "multi/single"})
    for r in rows:
        print(json.dumps(r), flush=True)

    import numpy as np

    import ray_tpu as rt
    from ray_tpu.core.runtime_context import require_runtime
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy)

    rt.init(num_cpus=2)
    try:
        runtime = require_runtime()
        extra = runtime.add_node(num_cpus=2)

        # --- pull bandwidth: object sealed on the extra node, pulled by
        # the driver's node manager over the scatter-gather chunk path.
        @rt.remote
        def produce(nbytes: int):
            import numpy as _np

            return _np.full(nbytes, 7, dtype=_np.uint8)

        pull_mib = 64
        ref = produce.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(
                node_id=extra.node_id)).remote(pull_mib << 20)
        rt.wait([ref], timeout=120)
        home_addr = runtime.nodes()[0]["address"]
        from ray_tpu.core.config import GLOBAL_CONFIG as _cfg

        t0 = time.perf_counter()
        ok = runtime._pool.get(home_addr).call(
            "pull_object", ref.id().binary(), 60_000, timeout=90)
        dt = time.perf_counter() - t0
        rows.append({
            "metric": "pull_gbps",
            "value": round((pull_mib << 20) / dt / 1e9, 2) if ok else 0.0,
            "unit": "GB/s", "object_mib": pull_mib,
            "chunk_bytes": int(_cfg.object_transfer_chunk_bytes)})
        print(json.dumps(rows[-1]), flush=True)

        # --- n x n actor calls with a numpy array argument (the
        # actor_calls_with_arg_async_n_n shape).
        import threading

        @rt.remote
        class Sink:
            def take(self, arr):
                return arr.nbytes

        n_actors = 4
        actors = [Sink.remote() for _ in range(n_actors)]
        rt.get([a.take.remote(np.zeros(8, np.uint8)) for a in actors],
               timeout=120)  # boot + compile path
        arg = np.zeros(32 << 10, np.uint8)
        counts = [0] * n_actors
        stop_at = time.perf_counter() + 3.0

        def caller(i):
            a = actors[i]
            while time.perf_counter() < stop_at:
                futs = [a.take.remote(arg) for _ in range(32)]
                rt.get(futs, timeout=60)
                counts[i] += len(futs)

        t0 = time.perf_counter()
        threads = [threading.Thread(target=caller, args=(i,))
                   for i in range(n_actors)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        elapsed = time.perf_counter() - t0
        rows.append({"metric": "actor_args_nn_per_s",
                     "value": round(sum(counts) / elapsed, 1),
                     "unit": "calls/s", "actors": n_actors,
                     "arg_bytes": int(arg.nbytes)})
        print(json.dumps(rows[-1]), flush=True)
    finally:
        rt.shutdown()


def _dataplane_rows() -> list:
    """Run the dataplane child; returns its rows (or one error row)."""
    try:
        proc = _run(["--dataplane-child"], DATAPLANE_TIMEOUT_S,
                    env_extra={"JAX_PLATFORMS": "cpu"})
    except subprocess.TimeoutExpired:
        return [{"metric": "dataplane",
                 "error": f"timeout {DATAPLANE_TIMEOUT_S}s"}]
    lines = _json_lines(proc.stdout)
    if lines and proc.returncode == 0:
        return lines
    tail = (proc.stderr or proc.stdout).strip().splitlines()[-3:]
    out = lines or []
    out.append({"metric": "dataplane",
                "error": "rc=%d: %s" % (proc.returncode, " | ".join(tail))})
    return out


def dataplane_main() -> int:
    """Standalone ``--dataplane``: rows + one merged tail line."""
    rows = _dataplane_rows()
    for r in rows:
        print(json.dumps(r), flush=True)
    print(json.dumps(_merge_dataplane_rows(rows)))
    return 0 if all("error" not in r for r in rows) else 1


def _merge_dataplane_rows(rows: list) -> dict:
    by = {r.get("metric"): r for r in rows}
    merged = {"metric": "dataplane"}
    for k in ("single_put_gbps", "multi_put_gbps", "put_scaling_ratio",
              "pull_gbps", "actor_args_nn_per_s"):
        if k in by and "error" not in by[k]:
            merged[k] = by[k].get("value")
    errs = [r["error"] for r in rows if "error" in r]
    if errs:
        merged["error"] = errs[0]
    return merged


# --------------------------------------------------------------------------
# chaos suite (--chaos): fault-recovery times on a real subprocess cluster
# --------------------------------------------------------------------------

def chaos_child_main() -> None:
    """Kill the head mid-workload and the only holder of an object, and
    time the recovery paths (supervisor respawn + durable-table reload +
    node re-registration/holder republish; lineage re-execution). Prints
    one JSON row. No chaos PLAN here — the faults are real SIGKILLs from
    the bench driver, so the row measures the recovery machinery
    end-to-end exactly as a production fault would exercise it."""
    _pin_platform()
    import os as _os
    import signal as _signal

    import numpy as _np

    import ray_tpu as rt
    from ray_tpu.core.runtime_context import require_runtime
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy)

    rt.init(num_cpus=2)
    runtime = require_runtime()

    @rt.remote
    def ping(i):
        return i

    # Warm: pool + leases exist, a background workload is in flight.
    assert rt.get([ping.remote(i) for i in range(4)],
                  timeout=120) == list(range(4))

    @rt.remote
    class Probe:
        def ok(self):
            return "ok"

    # --- head_recovery_s: SIGKILL the head, then time a NEW
    # head-dependent submission (actor creation must traverse
    # register_actor -> pick -> lease -> create on the RESPAWNED head).
    background = [ping.remote(i) for i in range(8)]  # mid-workload
    _os.kill(runtime._head_proc.pid, _signal.SIGKILL)
    t0 = time.perf_counter()
    probe = Probe.remote()
    assert rt.get(probe.ok.remote(), timeout=180) == "ok"
    head_recovery_s = time.perf_counter() - t0
    assert rt.get(background, timeout=180) == list(range(8))
    rt.kill(probe)

    # --- object_reconstruction_s: the ONLY holder of a task output is
    # SIGKILLed; get() must complete via lineage re-execution.
    node_b = runtime.add_node(num_cpus=2)
    time.sleep(1.5)
    n = 1_000_000

    @rt.remote(scheduling_strategy=NodeAffinitySchedulingStrategy(
        node_id=node_b.node_id, soft=True))
    def produce():
        return _np.arange(n)

    ref = produce.remote()
    ready, _ = rt.wait([ref], num_returns=1, timeout=120,
                       fetch_local=False)
    assert ready, "produce timed out"
    runtime.kill_node(node_b)
    t0 = time.perf_counter()
    got = rt.get(ref, timeout=180)
    object_reconstruction_s = time.perf_counter() - t0
    assert got[0] == 0 and got[-1] == n - 1

    # --- head_upgrade_s: rolling head upgrade (drain -> sqlite
    # checkpoint -> port handover to a NEW incarnation) under continuous
    # task + actor-call load. Acceptance is ZERO failed client requests
    # (latency may spike while requests ride retries across the gap) —
    # asserted here, so a row with head_upgrade_s implies it held.
    from ray_tpu.devtools import chaos as _chaos_mod

    @rt.remote(max_restarts=1, max_task_retries=-1)
    class UpgradeEcho:
        def hit(self, i):
            return i

    echo = UpgradeEcho.remote()
    assert rt.get(echo.hit.remote(-1), timeout=60) == -1

    def _upgrade_request(i):
        if i % 2:
            assert rt.get(ping.remote(i), timeout=120) == i
        else:
            assert rt.get(echo.hit.remote(i), timeout=120) == i

    up = _chaos_mod.run_rolling_upgrade(runtime, _upgrade_request,
                                        clients=2)
    assert up["request_failures"] == [], \
        f"requests failed during rolling upgrade: {up['request_failures']}"
    assert up["new_incarnation"] != up["old_incarnation"]
    head_upgrade_s = up["upgrade_s"]
    upgrade_requests_ok = up["requests_ok"]
    rt.kill(echo)

    # --- leak check: after the workload drains, the cluster-wide lease
    # census must be empty (every fault path returned its lease). A
    # census with an unreachable node is NOT leak-free — it is
    # incomplete; keep polling until every alive node answered (the
    # health sweep removes the killed node from the census set).
    leaked = None
    census_errors = None
    deadline = time.monotonic() + 45
    while time.monotonic() < deadline:
        census = runtime.head.retrying_call("cluster_leases", timeout=15)
        entries = [v for v in census.values() if isinstance(v, dict)]
        census_errors = [v["error"] for v in entries if "error" in v]
        leaked = [l for v in entries for l in v.get("leases", ())]
        if not leaked and not census_errors:
            break
        time.sleep(0.5)
    row = {
        "metric": "chaos_recovery",
        "head_recovery_s": round(head_recovery_s, 2),
        "object_reconstruction_s": round(object_reconstruction_s, 2),
        "head_upgrade_s": round(head_upgrade_s, 2),
        "upgrade_requests_ok": upgrade_requests_ok,
        "leaked_leases": len(leaked) if leaked is not None else -1,
        "object_bytes": n * 8, "nodes": 2,
    }
    if census_errors:
        row["census_error"] = census_errors[0]
    _witness_log_hits: dict = {}

    def _log_witness_hits(marker: bytes, fresh: bool = False) -> int:
        """Count witness lines across this session's process logs (read
        BEFORE shutdown — the session log dir is restored after it).
        Both witness markers are counted in ONE pass over the logs and
        memoized — the chaos child always runs with both flags on, and
        re-reading every worker log per marker doubles teardown I/O for
        nothing. ``fresh=True`` re-scans: the res verdict runs AFTER an
        up-to-20s settle window, and a late imbalance line (a worker's
        engine-close report — workers are not in the dump_flight poll
        set) must not hide behind a pre-settle snapshot."""
        from ray_tpu.core.config import GLOBAL_CONFIG as _gcfg

        markers = (b"RTPU_DEBUG_RPC:", b"RTPU_DEBUG_RES:",
                   b"RTPU_CHAN:")
        if fresh or not _witness_log_hits:
            _witness_log_hits.clear()
            _witness_log_hits.update({m: 0 for m in markers})
            try:
                for fn in _os.listdir(_gcfg.log_dir):
                    p = _os.path.join(_gcfg.log_dir, fn)
                    if _os.path.isfile(p):
                        with open(p, "rb") as fh:
                            data = fh.read()
                        for m in markers:
                            _witness_log_hits[m] += data.count(m)
            except OSError:
                pass
        return _witness_log_hits.get(marker, 0)

    def _poll_flight_payloads() -> list:
        """dump_flight payloads from the head + every alive node (the
        one RPC every process serves — both witnesses ride it)."""
        peers = [runtime.head.call("dump_flight", timeout=10)]
        for nv in runtime.head.call("list_nodes", timeout=10):
            if nv.get("alive"):
                peers.append(runtime._pool.get(nv["address"]).call(
                    "dump_flight", timeout=10))
        return peers

    if _os.environ.get("RTPU_DEBUG_RPC") == "1":
        # RPC-contract witness status: the whole recovery run executed
        # with duplicate delivery injected on every idempotent request
        # and per-(sender,receiver) outbox sequence checks. "Clean"
        # means zero violations in the driver's registry AND zero
        # RTPU_DEBUG_RPC: lines across this session's head/node/worker
        # logs (read BEFORE shutdown — the session log dir is restored
        # after it).
        from ray_tpu.devtools import rpc_debug as _rpcdbg

        log_hits = _log_witness_hits(b"RTPU_DEBUG_RPC:")
        # Cluster-wide witness stats ride the flight-dump payloads (the
        # one RPC every process serves): aggregate the driver's own
        # registry with the head's and every alive node's, so the row
        # proves duplicate injection actually COVERED the server side.
        viol = len(_rpcdbg.violations())
        dups = sum(_rpcdbg.dup_audit_counts().values())
        try:
            for payload in _poll_flight_payloads():
                rd = (payload or {}).get("rpc_debug") or {}
                viol += int(rd.get("violations", 0))
                dups += int(rd.get("dup_audits", 0))
        except Exception as e:
            row["rpc_witness_poll_error"] = repr(e)[:120]
        # Registry aggregate and log scan are overlapping evidence (a
        # live server's violation appears in BOTH): report them as
        # separate fields rather than a double-counting sum. Clean
        # requires both zero — the log scan also covers processes that
        # died before they could be polled.
        row["rpc_witness_clean"] = bool(viol == 0 and log_hits == 0)
        row["rpc_witness_violations"] = viol
        row["rpc_witness_log_lines"] = log_hits
        row["rpc_dup_audits"] = dups
    if _os.environ.get("RTPU_DEBUG_RES") == "1":
        # Resource-lifetime witness verdict: after the workload drains,
        # the CLUSTER-WIDE balance registries (driver + head + every
        # alive node, over the same dump_flight channel) must show zero
        # outstanding leak-kind resources — BufferLease pins, node
        # lease-table entries, KV speculation reservations. Transient
        # in-flight acquisitions settle within the retry window; a real
        # leak (the PR 2/PR 8 shapes) never does.
        from ray_tpu.devtools import res_debug as _resdbg

        leaked = None
        res_acquires = 0
        peer_viol = 0
        poll_error = None
        res_deadline = time.monotonic() + 20
        while time.monotonic() < res_deadline:
            own = _resdbg.dump_payload()
            leaked = own["leaked"]
            res_acquires = sum(own["acquired"].values())
            peer_viol = 0
            poll_error = None
            try:
                for payload in _poll_flight_payloads():
                    rd = (payload or {}).get("res_debug") or {}
                    leaked += int(rd.get("leaked", 0))
                    res_acquires += sum(
                        (rd.get("acquired") or {}).values())
                    # Peer violation counts ride the same payload: a
                    # node/head check_balanced failure (e.g. a "thread"
                    # imbalance, which is not a LEAK_KIND and never
                    # shows in `leaked`) must not pass the verdict —
                    # and the head's stdout is a PIPE, so its
                    # RTPU_DEBUG_RES: lines never reach the log scan.
                    peer_viol += int(rd.get("violations", 0))
            except Exception as e:
                # A transient poll failure (a node mid-respawn) is
                # RETRIED until the deadline, like a nonzero leak; it
                # neither passes a verdict built from partial data nor
                # fails the run off one dropped frame. Only the LAST
                # lap's outcome stands — incomplete = not clean, the
                # same rule the lease census applies.
                poll_error = repr(e)[:120]
                leaked = None
            if leaked == 0:
                break
            time.sleep(0.5)
        if poll_error is not None:
            row["res_witness_poll_error"] = poll_error
        res_viol = len(_resdbg.violations()) + peer_viol
        # Fresh scan AFTER the settle window: a worker's late
        # RTPU_DEBUG_RES line is this verdict's only evidence channel.
        res_log_hits = _log_witness_hits(b"RTPU_DEBUG_RES:",
                                         fresh=True)
        row["leaked_resources"] = leaked if leaked is not None else -1
        # Coverage evidence, like rpc_dup_audits: a leaked_resources=0
        # verdict over zero observed acquires would be vacuous.
        row["res_acquires_audited"] = res_acquires
        row["res_witness_clean"] = bool(leaked == 0 and res_viol == 0
                                        and res_log_hits == 0)
        row["res_witness_violations"] = res_viol
        row["res_witness_log_lines"] = res_log_hits
    if _os.environ.get("RTPU_DEBUG_CHAN") == "1":
        # Channel-protocol witness verdict: every ring/peer frame the
        # recovery run moved was checked online (seq/credit/cursor
        # invariants, sampled payload checksums, Lamport clocks).
        # Cluster-wide aggregation rides dump_flight like the other two
        # witnesses; the RTPU_CHAN: log scan covers processes that died
        # before the poll. frames_witnessed is the coverage evidence —
        # a 0-violation verdict over 0 frames is vacuous.
        from ray_tpu.devtools import chan_debug as _chandbg

        chan_frames = _chandbg.frames_witnessed()
        chan_viol = len(_chandbg.violations())
        try:
            for payload in _poll_flight_payloads():
                cd = (payload or {}).get("chan_debug") or {}
                chan_frames += int(cd.get("frames", 0))
                chan_viol += int(cd.get("violations", 0))
        except Exception as e:
            row["chan_witness_poll_error"] = repr(e)[:120]
        chan_log_hits = _log_witness_hits(b"RTPU_CHAN:")
        row["chan_frames_witnessed"] = chan_frames
        row["chan_violations"] = chan_viol
        row["chan_witness_log_lines"] = chan_log_hits
        row["chan_witness_clean"] = bool(chan_viol == 0
                                         and chan_log_hits == 0)
    print(json.dumps(row), flush=True)
    rt.shutdown()


def _chaos_rows() -> list:
    try:
        # RTPU_DEBUG_RPC=1: the recovery suite doubles as the RPC
        # contract audit — duplicate delivery on idempotent methods,
        # outbox sequence checks, classification-hole refusal — and the
        # row records witness-clean status alongside the timings.
        # RTPU_DEBUG_RES=1 alongside: the same run also audits resource
        # lifetimes — every BufferLease pin, node lease grant, and KV
        # reservation must settle (cluster-wide leaked_resources == 0).
        # RTPU_DEBUG_CHAN=1 completes the triple: every channel frame
        # the run moves is protocol-checked online (chan_violations==0).
        proc = _run(["--chaos-child"], CHAOS_TIMEOUT_S,
                    env_extra={"JAX_PLATFORMS": "cpu",
                               "RTPU_DEBUG_RPC": "1",
                               "RTPU_DEBUG_RES": "1",
                               "RTPU_DEBUG_CHAN": "1"})
    except subprocess.TimeoutExpired:
        return [{"metric": "chaos_recovery",
                 "error": f"timeout {CHAOS_TIMEOUT_S}s"}]
    lines = _json_lines(proc.stdout)
    if lines and proc.returncode == 0:
        return lines
    tail = (proc.stderr or proc.stdout).strip().splitlines()[-3:]
    out = lines or []
    out.append({"metric": "chaos_recovery",
                "error": "rc=%d: %s" % (proc.returncode,
                                        " | ".join(tail))})
    return out


def chaos_main() -> int:
    """Standalone ``--chaos``: recovery rows + one merged tail line.
    Exit 1 on any error, a non-zero lease leak, or an incomplete
    census — the verify gate's 'leaked_leases: 0' must not pass at the
    exit-code level on a leaking or unverifiable run."""
    rows = _chaos_rows()
    for r in rows:
        print(json.dumps(r), flush=True)
    print(json.dumps(_merge_chaos_rows(rows)))
    clean = all("error" not in r and "census_error" not in r
                and r.get("leaked_leases", 0) == 0
                and r.get("rpc_witness_clean", True)
                and r.get("leaked_resources", 0) == 0
                and r.get("res_witness_clean", True)
                and r.get("chan_violations", 0) == 0
                and r.get("chan_witness_clean", True)
                for r in rows)
    return 0 if clean else 1


def _merge_chaos_rows(rows: list) -> dict:
    by = {r.get("metric"): r for r in rows}
    merged = {"metric": "chaos_recovery"}
    row = by.get("chaos_recovery", {})
    if "error" in row:
        merged["error"] = row["error"]
    else:
        for k in ("head_recovery_s", "object_reconstruction_s",
                  "head_upgrade_s", "upgrade_requests_ok",
                  "leaked_leases", "census_error", "rpc_witness_clean",
                  "rpc_witness_violations", "rpc_witness_log_lines",
                  "rpc_dup_audits", "leaked_resources",
                  "res_witness_clean", "res_witness_violations",
                  "res_witness_log_lines", "res_acquires_audited",
                  "chan_witness_clean", "chan_violations",
                  "chan_witness_log_lines", "chan_frames_witnessed"):
            if row.get(k) is not None:
                merged[k] = row[k]
    return merged


# --------------------------------------------------------------------------
# scale suite (--scale): head hot paths at 100 simulated nodes
# --------------------------------------------------------------------------

def scale_child_main() -> int:
    """Boot ONE head + N simulated in-process node managers (stubbed
    stores, real control plane: registration, versioned heartbeat sync,
    directory mirrors, lease census) and measure the head's hot paths at
    production node counts: RPC dispatch (pick_node with locality
    hints), object-directory lookups, the node-death/drain directory
    scrub, and the cluster-wide lease census. Prints one JSON row."""
    import hashlib
    import random as _random

    from ray_tpu.cluster import protocol as _protocol
    from ray_tpu.core.cluster_runtime import SimulatedCluster
    from ray_tpu.core.config import GLOBAL_CONFIG as _cfg

    n = int(os.environ.get("RTPU_SCALE_NODES", "100"))
    n_objects = int(os.environ.get("RTPU_SCALE_OBJECTS", "20000"))
    if n >= 500:
        # 1000 nodes at one beat/s would make the run a heartbeat fan-in
        # bench; stretch the beat (and the death threshold with it) so
        # the storm below measures dispatch, not backpressure.
        _cfg.set("health_check_period_ms", 5000)
    t0 = time.perf_counter()
    sim = SimulatedCluster(n)
    sim.wait_registered(60)
    boot_s = time.perf_counter() - t0
    rng = _random.Random(0)
    node_ids = [nd.node_id for nd in sim.nodes]

    def pctl(vals: list, p: float) -> float:
        vals = sorted(vals)
        return vals[min(len(vals) - 1, int(len(vals) * p))]

    # Seed the object directory: n_objects objects, 1-3 holders each,
    # shipped as one object_batch frame per node (the production wire
    # shape). Gives directory lookups + the drain scrub real work.
    oids = [hashlib.sha224(b"scale-obj-%d" % i).digest()
            for i in range(n_objects)]
    per_node: dict = {nid: [] for nid in node_ids}
    for oid in oids:
        for nid in rng.sample(node_ids, rng.randint(1, 3)):
            per_node[nid].append(("add", oid, 1 << 20))
    for nid, entries in per_node.items():
        sim.client.call("object_batch", nid, entries, timeout=30)

    # Head RPC dispatch: pick_node, alternating bare and locality-hinted
    # picks (the dispatch shape owners send), p99 over 2000 calls.
    lat_pick = []
    for i in range(2000):
        hints = ([oids[rng.randrange(n_objects)] for _ in range(4)]
                 if i % 2 else None)
        t = time.perf_counter()
        picked = sim.client.call("pick_node", {"CPU": 1.0}, None, None,
                                 f"scale-k{i % 64}", hints, timeout=30)
        lat_pick.append((time.perf_counter() - t) * 1e6)
        assert picked is not None
    # Directory lookups: object_locations p99 over 2000 random objects.
    lat_loc = []
    for i in range(2000):
        t = time.perf_counter()
        sim.client.call("object_locations",
                        oids[rng.randrange(n_objects)],
                        node_ids[rng.randrange(n)], timeout=30)
        lat_loc.append((time.perf_counter() - t) * 1e6)
    # Task storm: sustained owner-side dispatch against a few hot
    # scheduling keys, A/B in the same window — per-task head pick +
    # lease (the pre-block path) vs owner-routed lease blocks (grant
    # once per block, then node-direct request_lease until exhaustion).
    from ray_tpu.cluster.protocol import ClientPool as _ClientPool

    storm_tasks = int(os.environ.get("RTPU_SCALE_STORM_TASKS", "2000"))
    storm_keys = int(os.environ.get("RTPU_SCALE_STORM_KEYS", "8"))
    pool = _ClientPool()
    res = {"CPU": 1.0}

    def storm(use_blocks: bool) -> dict:
        head_rpcs = 0
        direct = 0
        done = 0
        blocks: dict = {}
        t0 = time.perf_counter()
        for i in range(storm_tasks):
            key = f"storm-k{i % storm_keys}"
            granted = None
            addr = None
            used_head = False
            if use_blocks:
                blk = blocks.get(key)
                if blk is not None and blk[2] > 0:
                    bid, addr, remaining = blk
                    granted = pool.get(addr).call(
                        "request_lease", res, True, None,
                        uuid.uuid4().hex, "bench-owner", None, None,
                        bid, timeout=30)
                    if granted is None or isinstance(granted, dict):
                        granted = None
                        blocks.pop(key, None)
                    else:
                        blocks[key] = (bid, addr, remaining - 1)
                if granted is None:
                    # First touch / exhausted: one head grant renews a
                    # whole block of node-direct admissions.
                    used_head = True
                    head_rpcs += 1
                    bid = uuid.uuid4().hex
                    got = sim.client.call("lease_block_grant", bid,
                                          "bench-owner", res, None,
                                          None, timeout=30)
                    if got is None:
                        continue
                    _nid, addr, size, _ttl = got
                    granted = pool.get(addr).call(
                        "request_lease", res, True, None,
                        uuid.uuid4().hex, "bench-owner", None, None,
                        bid, timeout=30)
                    if granted is None or isinstance(granted, dict):
                        continue
                    blocks[key] = (bid, addr, size - 1)
            else:
                used_head = True
                head_rpcs += 1
                picked = sim.client.call("pick_node", res, None, None,
                                         key, None, timeout=30)
                if picked is None:
                    continue
                addr = picked[1]
                granted = pool.get(addr).call(
                    "request_lease", res, True, None, uuid.uuid4().hex,
                    "bench-owner", None, None, None, timeout=30)
                if granted is None or isinstance(granted, dict):
                    continue
            pool.get(addr).call("return_lease", granted[1], timeout=30)
            done += 1
            if not used_head:
                direct += 1
        dt = time.perf_counter() - t0
        for bid, _addr, _rem in blocks.values():
            sim.client.call("lease_block_revoke", bid, timeout=30)
        return {"tasks_per_s": round(done / dt, 1) if dt else None,
                "bypass_rate": round(direct / done, 4) if done else None,
                "head_rpcs_per_task": round(head_rpcs / done, 4)
                if done else None,
                "completed": done}

    head_path = storm(use_blocks=False)
    block_path = storm(use_blocks=True)
    pool.close_all()

    # Cluster-wide lease census (fan-out to all N nodes).
    t = time.perf_counter()
    census = sim.client.call("cluster_leases", timeout=60)
    census_ms = (time.perf_counter() - t) * 1e3
    census_errors = sum(1 for v in census.values()
                        if isinstance(v, dict) and "error" in v)
    # Node drain: the directory scrub that also runs per dead node.
    t = time.perf_counter()
    sim.client.call("drain_node", node_ids[-1], timeout=60)
    drain_ms = (time.perf_counter() - t) * 1e3
    # Heartbeat fan-in: the in-process head's per-handler stats cover
    # every beat the N nodes sent since boot.
    hb = _protocol.get_event_stats().get("heartbeat", {})
    hb_count = int(hb.get("count", 0))
    row = {
        "metric": "head_scale",
        "nodes": n,
        "objects": n_objects,
        "boot_s": round(boot_s, 2),
        "head_dispatch_us_p50": round(pctl(lat_pick, 0.50), 1),
        "head_dispatch_us_p99": round(pctl(lat_pick, 0.99), 1),
        "head_object_locations_us_p99": round(pctl(lat_loc, 0.99), 1),
        "head_census_ms": round(census_ms, 1),
        "head_census_errors": census_errors,
        "head_drain_scrub_ms": round(drain_ms, 1),
        "storm_tasks_per_s": block_path["tasks_per_s"],
        "storm_tasks_per_s_headpath": head_path["tasks_per_s"],
        "head_dispatch_bypass_rate": block_path["bypass_rate"],
        "head_rpcs_per_task": block_path["head_rpcs_per_task"],
        "head_rpcs_per_task_headpath": head_path["head_rpcs_per_task"],
        "storm_tasks_completed": block_path["completed"],
        "heartbeats_processed": hb_count,
        "head_heartbeat_handler_us_avg": round(
            hb.get("total_s", 0.0) / hb_count * 1e6, 1) if hb_count else None,
        "head_heartbeat_handler_ms_max": round(
            hb.get("max_s", 0.0) * 1e3, 2) if hb_count else None,
    }
    print(json.dumps(row), flush=True)
    sim.shutdown()
    return 0


def _scale_rows() -> list:
    # 1000-node runs (RTPU_SCALE_NODES=1000) boot 10x the node threads
    # and heartbeat fan-in: give the child a proportionally wider window.
    timeout_s = SCALE_TIMEOUT_S
    if int(os.environ.get("RTPU_SCALE_NODES", "100")) >= 500:
        timeout_s = SCALE_TIMEOUT_S * 4
    try:
        proc = _run(["--scale-child"], timeout_s,
                    env_extra={"JAX_PLATFORMS": "cpu"})
    except subprocess.TimeoutExpired:
        return [{"metric": "head_scale",
                 "error": f"timeout {timeout_s}s"}]
    lines = _json_lines(proc.stdout)
    if lines and proc.returncode == 0:
        return lines
    tail = (proc.stderr or proc.stdout).strip().splitlines()[-3:]
    out = lines or []
    out.append({"metric": "head_scale",
                "error": "rc=%d: %s" % (proc.returncode,
                                        " | ".join(tail))})
    return out


def scale_main() -> int:
    rows = _scale_rows()
    for r in rows:
        print(json.dumps(r), flush=True)
    return 0 if all("error" not in r for r in rows) else 1


# --------------------------------------------------------------------------
# dag suite (--dag): per-hop channel latency vs task-RPC round trip
# --------------------------------------------------------------------------

def dag_child_main() -> int:
    """Compiled-DAG channel hop latency vs the equivalent task-RPC
    round trip, same payload sizes, same node. Three measurements:

    - ``dag_hop_us_p50_*``: a raw one-way shm-ring hop (ping-pong over
      two rings / 2) — the steady-state per-edge cost the compiled DAG
      pays per message.
    - ``dag_exec_us_p50_*``: a full ``compiled.execute().get()`` round
      (driver→actor→driver: 2 channel hops + the actor loop).
    - ``task_rpc_us_p50_*``: ``actor.echo.remote(payload)`` + ``get``
      — the path a non-compiled call takes through lease/RPC/store.

    The ROADMAP acceptance is hop ≥10x under the task-RPC round trip."""
    import multiprocessing as _mp
    import uuid as _uuid

    import ray_tpu
    from ray_tpu.dag import InputNode
    from ray_tpu.dag.ring import RingChannel

    def _p50_us(samples: list) -> float:
        return round(sorted(samples)[len(samples) // 2] * 1e6, 1)

    row = {"metric": "dag_channel", "config": "same-node"}

    # Raw ring hop (no cluster needed): a CHILD PROCESS echoes ring A
    # onto ring B; p50 round-trip / 2 = one-way hop. Cross-process is
    # the honest measurement — a same-process thread pair serializes on
    # the GIL and reads ~10x slower than the real two-process hop.
    def _echo_proc(ca_, cb_, n_):
        ra_ = RingChannel(ca_, capacity=8)
        wb_ = RingChannel(cb_, capacity=8)
        for i in range(n_):
            wb_.write(ra_.read(i, timeout=30), i)
        ra_.close(unlink=True)
        wb_.close()

    def _ring_hop_p50(nbytes: int, n: int = 300) -> float:
        payload = b"x" * nbytes
        ca, cb = _uuid.uuid4().bytes, _uuid.uuid4().bytes
        proc = _mp.get_context("fork").Process(
            target=_echo_proc, args=(ca, cb, n), daemon=True)
        proc.start()
        wa = RingChannel(ca, capacity=8)
        rb = RingChannel(cb, capacity=8)
        samples = []
        for i in range(n):
            t0 = time.perf_counter()
            wa.write(payload, i)
            rb.read(i, timeout=30)
            samples.append((time.perf_counter() - t0) / 2)
        proc.join(timeout=30)
        wa.close()
        rb.close(unlink=True)
        return _p50_us(samples[n // 4:])

    for name, nbytes in (("4KB", 4096), ("256KB", 256 * 1024)):
        row[f"dag_hop_us_p50_{name}"] = _ring_hop_p50(nbytes)

    # RTPU_DEBUG_CHAN arm, 4KB hop: the witness must stay a debug tool,
    # not a tax — the row records its on-vs-off overhead (target <5%)
    # and gates on zero protocol violations over the witnessed frames.
    # The env flag is set before the fork so BOTH endpoints (parent
    # writer/reader and the echo child) run their hooks; the verdict
    # below covers the parent-side registry (the child's violations
    # print RTPU_CHAN: lines on the shared stdout).
    from ray_tpu.devtools import chan_debug as _chandbg

    os.environ["RTPU_DEBUG_CHAN"] = "1"
    _chandbg.reset()
    try:
        witness_us = _ring_hop_p50(4096)
    finally:
        os.environ.pop("RTPU_DEBUG_CHAN", None)
    row["dag_hop_us_p50_4KB_witness"] = witness_us
    base_us = row["dag_hop_us_p50_4KB"]
    row["dag_witness_overhead_pct"] = round(
        100.0 * (witness_us - base_us) / base_us, 1)
    row["chan_frames_witnessed"] = _chandbg.frames_witnessed()
    row["chan_violations"] = len(_chandbg.violations())

    rt = ray_tpu.init(num_cpus=8)
    try:
        @ray_tpu.remote
        class Echo:
            def echo(self, x):
                return x

        a = Echo.remote()
        ray_tpu.get(a.echo.remote(b"warm"), timeout=120)
        for name, nbytes in (("4KB", 4096), ("256KB", 256 * 1024)):
            payload = b"x" * nbytes
            samples = []
            for _ in range(40):
                t0 = time.perf_counter()
                ray_tpu.get(a.echo.remote(payload), timeout=60)
                samples.append(time.perf_counter() - t0)
            row[f"task_rpc_us_p50_{name}"] = _p50_us(samples[10:])
            with InputNode() as inp:
                dag = a.echo.bind(inp)
            compiled = dag.experimental_compile()
            try:
                for _ in range(8):
                    compiled.execute(payload).get(timeout=60)
                samples = []
                for _ in range(60):
                    t0 = time.perf_counter()
                    compiled.execute(payload).get(timeout=60)
                    samples.append(time.perf_counter() - t0)
            finally:
                compiled.teardown()
            row[f"dag_exec_us_p50_{name}"] = _p50_us(samples[15:])
            hop = row[f"dag_hop_us_p50_{name}"]
            rpc = row[f"task_rpc_us_p50_{name}"]
            row[f"dag_hop_speedup_vs_rpc_{name}"] = round(rpc / hop, 1)
            row[f"dag_exec_speedup_vs_rpc_{name}"] = round(
                rpc / row[f"dag_exec_us_p50_{name}"], 2)
    finally:
        ray_tpu.shutdown()
    print(json.dumps(row), flush=True)
    return 0


def _dag_rows() -> list:
    try:
        proc = _run(["--dag-child"], DAG_TIMEOUT_S,
                    env_extra={"JAX_PLATFORMS": "cpu"})
    except subprocess.TimeoutExpired:
        return [{"metric": "dag_channel",
                 "error": f"timeout {DAG_TIMEOUT_S}s"}]
    lines = _json_lines(proc.stdout)
    if lines and proc.returncode == 0:
        return lines
    tail = (proc.stderr or proc.stdout).strip().splitlines()[-3:]
    out = lines or []
    out.append({"metric": "dag_channel",
                "error": "rc=%d: %s" % (proc.returncode,
                                        " | ".join(tail))})
    return out


def dag_bench_main() -> int:
    """Standalone ``--dag``: exit 1 on any error OR a channel-protocol
    violation from the witness arm — the hop numbers don't count if the
    frames that produced them broke the protocol."""
    rows = _dag_rows()
    for r in rows:
        print(json.dumps(r), flush=True)
    return 0 if all("error" not in r and r.get("chan_violations", 0) == 0
                    for r in rows) else 1


# --------------------------------------------------------------------------
# data suite (--data): channel-vs-task shuffle GB/s + ingest overlap A/B
# --------------------------------------------------------------------------

def data_child_main() -> int:
    """Streaming Dataset executor A/Bs, same window, alternating arms:

    - shuffle: ``random_shuffle`` of the same dataset with the exchange
      on the channel mesh vs the per-task-RPC pipeline (both transports
      share the partition/merge kernels, so the work per row is
      identical — the delta is pure transport).
    - ingest: a synthetic train loop over ``iter_batches(device_put=)``
      with the double-buffered background loader vs inline per-batch
      ``device_put`` on the consumer thread (the pre-executor path).
    """
    import numpy as np

    import ray_tpu
    import ray_tpu.data as rdata
    from ray_tpu.core.config import GLOBAL_CONFIG as cfg

    row = {"metric": "data_executor", "config": "same-node"}
    ray_tpu.init(num_cpus=4)
    try:
        # ---------------- shuffle GB/s, alternating A/B -----------------
        import ray_tpu.data._exchange as _ex

        # Many small blocks: steady-state per-piece cost is what the
        # transports differ on (the partition/merge kernels are shared),
        # and a 48-block exchange moves 48x48 pieces per pass.
        n_rows, width = 96_000, 16  # ~13 MB of float64 per pass
        ds = rdata.range(n_rows, parallelism=48).map_batches(
            lambda b: {"id": b["id"],
                       "x": np.tile(b["id"][:, None].astype(np.float64),
                                    (1, width))})
        nbytes = n_rows * (width + 1) * 8
        counts = {"channel": 0}
        orig = _ex._channel_exchange

        def counting(*a, **k):
            counts["channel"] += 1
            return orig(*a, **k)

        _ex._channel_exchange = counting
        ds.materialize()  # warm read path + compile nothing later
        times = {"channel": [], "task": []}
        reps = 3
        for rep in range(reps):
            for arm in ("channel", "task"):  # alternate inside the window
                cfg.data_exchange_transport = arm
                t0 = time.perf_counter()
                out = ds.random_shuffle(seed=rep).materialize()
                assert out.count() == n_rows
                times[arm].append(time.perf_counter() - t0)
        cfg.data_exchange_transport = "channel"
        gbps = {arm: round(nbytes / min(ts) / 1e9, 3)
                for arm, ts in times.items()}
        row["data_shuffle_gbps_channel"] = gbps["channel"]
        row["data_shuffle_gbps_task"] = gbps["task"]
        row["data_shuffle_channel_speedup"] = round(
            gbps["channel"] / gbps["task"], 2)
        # Honesty check: 0 here means every "channel" arm silently fell
        # back to tasks and the A/B measured nothing.
        row["data_channel_exchanges"] = counts["channel"]

        # ---------------- ingest overlap A/B ----------------------------
        import jax
        import jax.numpy as jnp

        dev = jax.devices()[0]
        d = 256
        bs = 4096
        ing = rdata.range(65_536, parallelism=16).map_batches(
            lambda b: {"x": np.tile(b["id"][:, None].astype(np.float32),
                                    (1, d))})
        w = jnp.ones((d, d), jnp.float32)

        @jax.jit
        def step(x, w_):
            y = x @ w_
            y = jnp.tanh(y) @ w_
            return (y @ w_).sum()

        step(jnp.ones((bs, d), jnp.float32), w).block_until_ready()

        def run_buffered():
            n = 0
            for b in ing.iter_batches(batch_size=bs, device_put=dev):
                step(b["x"], w).block_until_ready()
                n += 1
            return n

        def run_inline():
            n = 0
            for hb in ing.iter_batches(batch_size=bs):
                b = {k: jax.device_put(v, dev) for k, v in hb.items()}
                step(b["x"], w).block_until_ready()
                n += 1
            return n

        run_buffered()  # warm both pipelines once
        t_buf, t_inl = [], []
        for _ in range(2):
            t0 = time.perf_counter()
            n_steps = run_buffered()
            t_buf.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            assert run_inline() == n_steps
            t_inl.append(time.perf_counter() - t0)
        # Roofline: the same step count on a pre-staged device batch —
        # what steps/s looks like with ZERO ingest cost. buffered/
        # roofline is the "ingest stopped bottlenecking" ratio (needs
        # host cores for the loader thread to overlap into; on a 1-core
        # container both A/B arms are core-bound and converge instead).
        xb = jax.device_put(np.ones((bs, d), np.float32), dev)
        t0 = time.perf_counter()
        for _ in range(n_steps):
            step(xb, w).block_until_ready()
        t_roof = time.perf_counter() - t0
        row["data_ingest_steps_per_s_buffered"] = round(
            n_steps / min(t_buf), 2)
        row["data_ingest_steps_per_s_inline"] = round(
            n_steps / min(t_inl), 2)
        row["data_ingest_steps_per_s_roofline"] = round(
            n_steps / t_roof, 2)
        row["data_ingest_overlap_speedup"] = round(
            min(t_inl) / min(t_buf), 2)
        row["data_ingest_efficiency"] = round(
            t_roof / min(t_buf), 2)
        row["cpu_cores"] = len(os.sched_getaffinity(0))
    finally:
        ray_tpu.shutdown()
    print(json.dumps(row), flush=True)
    return 0


def _data_rows() -> list:
    try:
        proc = _run(["--data-child"], DATA_TIMEOUT_S,
                    env_extra={"JAX_PLATFORMS": "cpu"})
    except subprocess.TimeoutExpired:
        return [{"metric": "data_executor",
                 "error": f"timeout {DATA_TIMEOUT_S}s"}]
    lines = _json_lines(proc.stdout)
    if lines and proc.returncode == 0:
        return lines
    tail = (proc.stderr or proc.stdout).strip().splitlines()[-3:]
    out = lines or []
    out.append({"metric": "data_executor",
                "error": "rc=%d: %s" % (proc.returncode,
                                        " | ".join(tail))})
    return out


def data_bench_main() -> int:
    rows = _data_rows()
    for r in rows:
        print(json.dumps(r), flush=True)
    return 0 if all("error" not in r for r in rows) else 1


# --------------------------------------------------------------------------
# disagg serve sweep: colocated vs disaggregated p99 TTFT, mixed load
# --------------------------------------------------------------------------

def serve_disagg_child_main() -> int:
    """Mixed long-prompt + long-decode workload, equal replica budget:
    colocated (2 full replicas) vs disaggregated (1 prefill + 1
    decode). TTFT is measured with PROBE requests (max_new_tokens=1 —
    the request completes at its first token on both topologies), fired
    steadily while background threads keep long decodes and long
    prompts in flight. Disaggregation isolates the probe path from the
    decode load, which is what flattens p99."""
    import threading

    import ray_tpu
    import ray_tpu.serve as serve
    from ray_tpu.serve.llm import build_llm_deployment

    ek = dict(max_batch=4, max_len=288,
              prompt_buckets=[16, 32, 64, 128, 256], decode_chunk=4,
              prefill_chunk=32, seed=0)
    measure_s = 12.0
    ray_tpu.init(num_cpus=24)
    rows = []
    try:
        for mode in ("colocated", "disagg"):
            if mode == "colocated":
                dep = build_llm_deployment(
                    name=f"sw{mode}", num_replicas=2, engine_kwargs=ek)
            else:
                dep = build_llm_deployment(
                    name=f"sw{mode}", disaggregated=True,
                    num_prefill_replicas=1, num_decode_replicas=1,
                    engine_kwargs=ek)
            h = serve.run(dep)
            # Warm both paths (compiles prefill buckets + decode).
            h.remote({"prompt_ids": [7] * 16,
                      "max_new_tokens": 4}).result(timeout=600)
            h.remote({"prompt_ids": list(range(1, 225)),
                      "max_new_tokens": 2}).result(timeout=600)
            stop = threading.Event()
            errors = []

            def _bg(fn):
                def run():
                    i = 0
                    while not stop.is_set():
                        try:
                            fn(i)
                        except Exception as e:  # noqa: BLE001 — recorded
                            errors.append(repr(e))
                            if len(errors) > 20:
                                return
                        i += 1
                t = threading.Thread(target=run, daemon=True)
                t.start()
                return t

            def long_decode(i):
                # Decode-dominated stream: a cheap 16-token prefill
                # then 96 decode steps. In the colocated topology these
                # keep BOTH replicas' engines decoding (probe prefills
                # queue behind decode ticks); disaggregated, they live
                # on the decode replica and the probe path stays clear.
                h.remote({"prompt_ids": [(i * 7 + j) % 251 + 1
                                         for j in range(16)],
                          "max_new_tokens": 96}).result(timeout=300)

            def long_prompt(i):
                # Bursty long prompts (throttled to a fixed rate so
                # both topologies see the same long-prompt load — an
                # unthrottled stream just saturates whatever prefill
                # capacity exists and measures replica COUNT, not
                # topology).
                h.remote({"prompt_ids": [(i * 13 + j) % 251 + 1
                                         for j in range(224)],
                          "max_new_tokens": 2}).result(timeout=300)
                time.sleep(0.6)

            bgs = [_bg(long_decode), _bg(long_decode), _bg(long_decode),
                   _bg(long_prompt)]
            time.sleep(2.0)  # let the background load saturate
            probes = []
            t_end = time.monotonic() + measure_s
            while time.monotonic() < t_end:
                t0 = time.perf_counter()
                h.remote({"prompt_ids": [3] * 16,
                          "max_new_tokens": 1}).result(timeout=300)
                probes.append((time.perf_counter() - t0) * 1e3)
                time.sleep(0.05)
            stop.set()
            for t in bgs:
                t.join(timeout=60)
            probes.sort()
            rows.append({
                "metric": f"serve_disagg_{mode}",
                "config": "tiny-cpu",
                "probes": len(probes),
                "p50_ttft_ms": round(probes[len(probes) // 2], 2),
                "p99_ttft_ms": round(
                    probes[min(len(probes) - 1,
                               int(len(probes) * 0.99))], 2),
                "bg_errors": len(errors),
            })
    finally:
        try:
            serve.shutdown()
        finally:
            ray_tpu.shutdown()
    for r in rows:
        print(json.dumps(r), flush=True)
    return 0


def _serve_disagg_rows() -> list:
    try:
        proc = _run(["--serve-disagg-child"], DISAGG_TIMEOUT_S,
                    env_extra={"JAX_PLATFORMS": "cpu"})
    except subprocess.TimeoutExpired:
        return [{"metric": "serve_disagg",
                 "error": f"timeout {DISAGG_TIMEOUT_S}s"}]
    lines = _json_lines(proc.stdout)
    if lines and proc.returncode == 0:
        return lines
    tail = (proc.stderr or proc.stdout).strip().splitlines()[-3:]
    out = lines or []
    out.append({"metric": "serve_disagg",
                "error": "rc=%d: %s" % (proc.returncode,
                                        " | ".join(tail))})
    return out


def _merge_serve_disagg_rows(rows: list) -> dict:
    by = {r.get("metric"): r for r in rows}
    merged: dict = {"metric": "serve_disagg"}
    err = next((r["error"] for r in rows if "error" in r), None)
    colo = by.get("serve_disagg_colocated", {})
    dis = by.get("serve_disagg_disagg", {})
    if err:
        merged["error"] = err
        return merged
    if colo.get("p99_ttft_ms") and dis.get("p99_ttft_ms"):
        merged["serve_colo_p99_ttft_ms"] = colo["p99_ttft_ms"]
        merged["serve_disagg_p99_ttft_ms"] = dis["p99_ttft_ms"]
        merged["serve_colo_p50_ttft_ms"] = colo.get("p50_ttft_ms")
        merged["serve_disagg_p50_ttft_ms"] = dis.get("p50_ttft_ms")
        merged["serve_disagg_ttft_flatness"] = round(
            colo["p99_ttft_ms"] / dis["p99_ttft_ms"], 2)
    return merged


def serve_disagg_main() -> int:
    rows = _serve_disagg_rows()
    for r in rows:
        print(json.dumps(r), flush=True)
    print(json.dumps(_merge_serve_disagg_rows(rows)))
    return 0 if all("error" not in r for r in rows) else 1


# --------------------------------------------------------------------------
# fleet KV tier: spill/pull vs recompute, same-window A/B with churn
# --------------------------------------------------------------------------

def kv_fleet_child_main() -> int:
    """PR 9's prefix sweep extended to the fleet KV tier (PR 18): two
    single-slot engines share a page store, and round-robin group
    traffic makes every admission evict the previous group — so the
    fleet tier (spill on evict, pull on re-admission) is the ONLY
    prefix reuse available. Mid-sweep one engine is killed and
    replaced: its HBM cache dies, its spilled pages don't. The A/B
    alternates fleet off/on twice in the same window so drift can't
    masquerade as a win; post-kill TTFTs are reported separately
    (``p50_ttft_ms_churn`` — the metric the tier exists to flatten)."""
    from ray_tpu.models import llama
    from ray_tpu.serve.engine.kv_fleet import LocalKVPageStore
    from ray_tpu.serve.llm import LLMEngine

    BLOCK = 8
    GROUPS, TURNS = 6, 3
    # 80-token shared prefix (10 blocks) + 8-token per-turn suffix:
    # fleet-on re-admissions pull 10 pages + prefill an 8-bucket tail,
    # fleet-off recomputes the whole 88 tokens in the 96 bucket.
    prefixes = [[(g * 97 + j) % 251 + 1 for j in range(80)]
                for g in range(GROUPS)]

    def prompt_for(g, turn):
        return prefixes[g] + [(g * 31 + turn * 7 + j) % 251 + 1
                              for j in range(8)]

    # Wider than tiny_config on purpose: recompute FLOPs grow with
    # d_model^2 while page bytes grow linearly, and the tier only pays
    # off when a block costs more to recompute than to copy. The
    # default tiny model is in the opposite (recompute-is-free) regime
    # — which the measured crossover on the "on" rows makes visible.
    cfg = llama.tiny_config(d_model=384, n_layers=6, n_heads=8,
                            n_kv_heads=2, d_ff=1536, max_seq_len=96)
    ek = dict(max_batch=1, max_len=96,
              prompt_buckets=[8, 16, 32, 64, 96], decode_chunk=4,
              seed=0, prefix_block=BLOCK)

    def new_engine(mode, store):
        if mode == "on":
            # Gate 0 = always pull; the MEASURED crossover is reported
            # alongside so the merged line shows what "auto" would do.
            return LLMEngine(cfg, kv_fleet_min_prefix_blocks=0,
                             kv_fleet_store=store, **ek)
        return LLMEngine(cfg, **ek)

    def warm(e):
        # Compile every program the sweep uses, off the clock.
        e.generate([5] * 88, max_new_tokens=1)
        e.generate([6] * 8, max_new_tokens=1)

    def eng_reused(e):
        st = e.stats()
        return (st.get("prefix_tokens_reused", 0)
                + st.get("kv_fleet_tokens_reused", 0))

    # Round-robin turns across groups (group -> engine by g % 2): the
    # slot is always evicted between a group's consecutive turns.
    sched = [(g, t) for t in range(TURNS) for g in range(GROUPS)]
    kill_at = len(sched) // 2

    rows = []
    for mode in ("off", "on", "off", "on"):  # same-window alternating
        store = LocalKVPageStore(capacity_bytes=256 << 20)
        engines = [new_engine(mode, store), new_engine(mode, store)]
        try:
            for e in engines:
                warm(e)
            baseline = [eng_reused(e) for e in engines]
            reused_total = 0
            prompt_tokens = 0
            ttfts, churn_ttfts = [], []
            for i, (g, t) in enumerate(sched):
                if i == kill_at:
                    # "Replica kill": engine 0's HBM cache dies with
                    # it. Bank its measured reuse, then rebuild and
                    # re-warm (restart compiles are off the clock —
                    # churn TTFT measures the CACHE loss, not XLA).
                    reused_total += eng_reused(engines[0]) - baseline[0]
                    engines[0].close()
                    engines[0] = new_engine(mode, store)
                    warm(engines[0])
                    baseline[0] = eng_reused(engines[0])
                e = engines[g % 2]
                p = prompt_for(g, t)
                t0 = time.perf_counter()
                e.generate(p, max_new_tokens=1)
                dt_ms = (time.perf_counter() - t0) * 1e3
                prompt_tokens += len(p)
                ttfts.append(dt_ms)
                if i >= kill_at:
                    churn_ttfts.append(dt_ms)
            reused_total += sum(eng_reused(e) - baseline[j]
                                for j, e in enumerate(engines))
            ttfts.sort()
            churn_ttfts.sort()
            row = {
                "metric": "kv_fleet_sweep",
                "config": "small-cpu",
                "mode": mode,
                "requests": len(sched),
                "hit_rate": round(reused_total / max(1, prompt_tokens),
                                  3),
                "p50_ttft_ms": round(ttfts[len(ttfts) // 2], 2),
                "p50_ttft_ms_churn": round(
                    churn_ttfts[len(churn_ttfts) // 2], 2),
            }
            if mode == "on":
                st = engines[1].stats()
                # The measured crossover table: store-side costs from
                # the start-of-engine probe, recompute side from real
                # prefill EWMAs accumulated during this sweep.
                for k in ("kv_fleet_pull_ms_per_page",
                          "kv_fleet_lookup_ms",
                          "kv_fleet_prefill_ms_per_block",
                          "kv_pull_vs_recompute_crossover_blocks",
                          "kv_fleet_spilled_blocks",
                          "kv_fleet_pulled_blocks",
                          "kv_fleet_rejects"):
                    row[k] = st.get(k)
            rows.append(row)
        finally:
            for e in engines:
                try:
                    e.close()
                except Exception:  # noqa: BLE001 — teardown best-effort
                    pass
    for r in rows:
        print(json.dumps(r), flush=True)
    return 0


def _kv_fleet_rows() -> list:
    try:
        proc = _run(["--kv-fleet-child"], KV_FLEET_TIMEOUT_S,
                    env_extra={"JAX_PLATFORMS": "cpu"})
    except subprocess.TimeoutExpired:
        return [{"metric": "kv_fleet",
                 "error": f"timeout {KV_FLEET_TIMEOUT_S}s"}]
    lines = _json_lines(proc.stdout)
    if lines and proc.returncode == 0:
        return lines
    tail = (proc.stderr or proc.stdout).strip().splitlines()[-3:]
    out = lines or []
    out.append({"metric": "kv_fleet",
                "error": "rc=%d: %s" % (proc.returncode,
                                        " | ".join(tail))})
    return out


def _merge_kv_fleet_rows(rows: list) -> dict:
    """Median across the repeated off/on phases (2 each): one headline
    pair per metric, so a single noisy phase can't flip the A/B."""
    merged: dict = {"metric": "kv_fleet"}
    err = next((r["error"] for r in rows if "error" in r), None)
    if err:
        merged["error"] = err
        return merged

    def med(vals):
        vals = sorted(v for v in vals if v is not None)
        return vals[len(vals) // 2] if vals else None

    on = [r for r in rows if r.get("mode") == "on"]
    off = [r for r in rows if r.get("mode") == "off"]
    if not on or not off:
        merged["error"] = "missing off/on phase rows"
        return merged
    merged["kv_fleet_hit_rate"] = med([r.get("hit_rate") for r in on])
    merged["kv_fleet_hit_rate_off"] = med(
        [r.get("hit_rate") for r in off])
    merged["kv_fleet_p50_ttft_ms_churn"] = med(
        [r.get("p50_ttft_ms_churn") for r in on])
    merged["kv_fleet_p50_ttft_ms_churn_off"] = med(
        [r.get("p50_ttft_ms_churn") for r in off])
    merged["kv_fleet_p50_ttft_ms"] = med(
        [r.get("p50_ttft_ms") for r in on])
    merged["kv_fleet_p50_ttft_ms_off"] = med(
        [r.get("p50_ttft_ms") for r in off])
    co = [r.get("kv_pull_vs_recompute_crossover_blocks") for r in on
          if r.get("kv_pull_vs_recompute_crossover_blocks") is not None]
    if co:
        merged["kv_pull_vs_recompute_crossover_blocks"] = co[-1]
    return merged


def kv_fleet_bench_main() -> int:
    rows = _kv_fleet_rows()
    for r in rows:
        print(json.dumps(r), flush=True)
    print(json.dumps(_merge_kv_fleet_rows(rows)))
    return 0 if all("error" not in r for r in rows) else 1


# --------------------------------------------------------------------------
# serve-scale suite: million-session router sim + QoS flood + streaming A/B
# --------------------------------------------------------------------------

def _scale_session_deck(n: int = 1_000_000,
                        space: int = 1_000_000) -> list:
    """A heavy-tailed (Pareto) deck of session ids: a handful of hot
    multi-turn sessions dominate while the tail spans ~1M distinct
    users — the popularity shape the session-affinity LRU and the
    prefix index are built for."""
    import random

    rng = random.Random(1234)
    return [min(int((rng.paretovariate(1.1) - 1.0) * 4000.0), space - 1)
            for _ in range(n)]


def _router_scale_sim(n_replicas: int, deck: list, templates: list,
                      chains: list, measure_s: float = 3.0) -> dict:
    """Route the session deck against ``n_replicas`` simulated load
    snapshots with NO cluster: the router's choose() hot path is what
    scales (candidate subsets + incremental rank + delta'd snapshot
    fan-in), so driving it directly measures decisions/s at fleet
    sizes the box can't boot. A ~1% delta sweep lands every ~0.5s —
    the controller-journal cadence — so freshness never lapses into
    the pow-2 fallback and the rank keeps absorbing O(touched)
    updates mid-measure."""
    import random

    from ray_tpu.devtools.lock_debug import make_lock
    from ray_tpu.serve._private.router import Router

    rng = random.Random(n_replicas)
    # Equal candidate pressure at every scale: ~20 replicas hold each
    # prompt-template chain (the affinity-candidate cap saturates), so
    # per-decision work is identical and the flatness ratio measures
    # the fleet-size dependence alone.
    P = max(8, min(len(templates), n_replicas // 20))

    def _snap(i, now):
        return {"ts": now, "queue_depth": (i * 7) % 5, "waiting": 0,
                "slots": 4, "kv_free_blocks": (i * 3) % 9,
                "kv_total_blocks": 8, "prefix_block_size": 4,
                "prefix_hashes": chains[i % P]}

    now = time.time()
    replicas = [f"r{i}" for i in range(n_replicas)]
    r = Router.__new__(Router)
    r._controller = None
    r._deployment = "scale-sim"
    r._lock = make_lock("serve.router._lock")
    r._replicas = []
    r._version = -1
    r._load_gen = -1
    r._loads = {}
    r._inflight = {}
    r._model_affinity = {}
    r._scored_routes = 0
    r._pow2_routes = 0
    r._affinity_routes = 0
    r._poller_started = True  # sim mode: never spawn the long-poller
    r._poll_thread = None
    r._stopped = False
    t0 = time.perf_counter()
    r._apply(1, replicas, 1, [_snap(i, now) for i in range(n_replicas)])
    apply_ms = (time.perf_counter() - t0) * 1e3
    sweep = max(1, n_replicas // 100)
    gen = 1
    decisions = 0
    sessions = set()
    di = rng.randrange(len(deck))
    # Warm the route path (first choose touches lazy state), then
    # measure a fixed wall window.
    r.done(r.choose(prefix_tokens=templates[0], session_key=deck[di]))
    t_next_delta = time.monotonic() + 0.5
    t_end = time.monotonic() + measure_s
    t_start = time.monotonic()
    while True:
        now_m = time.monotonic()
        if now_m >= t_end:
            break
        if now_m >= t_next_delta:
            gen += 1
            ups = {}
            for _ in range(sweep):
                i = rng.randrange(n_replicas)
                ups[i] = _snap(i, time.time())
            assert r._apply_delta(1, ups, load_gen=gen)
            t_next_delta = now_m + 0.5
            continue
        s = deck[di]
        di = (di + 1) % len(deck)
        sessions.add(s)
        choice = r.choose(prefix_tokens=templates[s % P], session_key=s)
        r.done(choice)
        decisions += 1
    span = time.monotonic() - t_start
    st = r.stats()
    scored = max(1, st["scored_routes"])
    return {
        "metric": f"serve_scale_router_{n_replicas}",
        "replicas": n_replicas,
        "decisions": decisions,
        "decisions_per_s": round(decisions / span, 1),
        "apply_full_ms": round(apply_ms, 2),
        "avg_candidates_scored": round(
            st["candidates_scored"] / scored, 2),
        "scored_frac": round(st["scored_routes"]
                             / max(1, decisions + 1), 4),
        "session_affinity_routes": st["session_affinity_routes"],
        "distinct_sessions": len(sessions),
        "deck_sessions": len(deck),
        "delta_sweeps": gen - 1,
    }


def _qos_flood_sim(measure_s: float = 3.0) -> dict:
    """Hostile-tenant flood against the WFQ admission gate, no
    cluster: 4 well-behaved tenants and one flooder firing ~50x its
    token budget. The contract is per-tenant isolation — the flooder
    sheds on ITS OWN bucket + queue while the good tenants' p99
    acquire latency stays flat."""
    import threading

    from ray_tpu.serve._private.slo import (AdmissionController,
                                            DeploymentOverloadedError)

    ac = AdmissionController(budget_ms=0.0, queue_depth=64,
                             queue_timeout_s=0.25, window=256,
                             min_samples=1, probe_inflight=4)
    ac.configure_tenant("flood", weight=1.0, tokens_per_s=20.0,
                        burst_tokens=10.0)
    good = [f"good{i}" for i in range(4)]
    stop = threading.Event()
    lat = {t: [] for t in good + ["flood"]}
    shed_local = {"flood": 0}
    lock = threading.Lock()

    def tenant_loop(t, cost, rate_hz):
        period = 1.0 / rate_hz
        while not stop.is_set():
            t0 = time.perf_counter()
            try:
                ac.acquire("d", tenant=t, cost=cost)
            except DeploymentOverloadedError:
                with lock:
                    shed_local[t] = shed_local.get(t, 0) + 1
                continue
            wait_ms = (time.perf_counter() - t0) * 1e3
            time.sleep(0.002)  # simulated service time
            ac.record_ttft("d", wait_ms + 2.0, tenant=t)
            ac.release("d", tenant=t)
            with lock:
                lat[t].append(wait_ms + 2.0)
            time.sleep(max(0.0, period - 0.002))

    threads = [threading.Thread(target=tenant_loop, args=(t, 5.0, 40.0),
                                daemon=True) for t in good]
    threads += [threading.Thread(target=tenant_loop,
                                 args=("flood", 5.0, 50.0), daemon=True)
                for _ in range(4)]  # ~200 req/s vs a 4 req/s budget
    for t in threads:
        t.start()
    time.sleep(measure_s)
    stop.set()
    for t in threads:
        t.join(timeout=10)

    def _p99(vals):
        if not vals:
            return None
        vals = sorted(vals)
        return round(vals[min(len(vals) - 1, int(len(vals) * 0.99))], 2)

    snap = ac.snapshot()["d"]["tenants"]
    good_p99 = max(_p99(lat[t]) or 0.0 for t in good)
    return {
        "metric": "serve_scale_qos",
        "good_p99_ttft_ms": good_p99,
        "good_admitted": sum(len(lat[t]) for t in good),
        "good_shed": sum(snap.get(t, {}).get("shed", 0) for t in good),
        "flood_p99_ttft_ms": _p99(lat["flood"]),
        "flood_admitted": len(lat["flood"]),
        "flood_shed": snap.get("flood", {}).get("shed", 0),
    }


def serve_scale_child_main() -> int:
    """Simulated-serve scale suite: (1) router decisions/s against
    100 -> 10k replica load snapshots under a ~1M-session heavy-tailed
    deck (flatness is the O(touched) acceptance bar), (2) WFQ flood
    isolation, (3) a REAL mini-cluster streaming-disagg A/B — p50
    TTFT of the token stream vs the non-streaming probe in the same
    window — then (4) the RTPU_DEBUG_RES leak census over all of it."""
    from ray_tpu.core.config import GLOBAL_CONFIG as cfg
    from ray_tpu.serve.engine.kv_manager import chain_hashes

    rows = []
    cfg.set("serve_router_policy", "scored")
    templates = [[(t * 7 + j) % 251 + 1 for j in range(12)]
                 for t in range(512)]
    chains = [chain_hashes(p, 4) for p in templates]
    deck = _scale_session_deck()
    for n in (100, 1000, 10000):
        rows.append(_router_scale_sim(n, deck, templates, chains))
    rows.append(_qos_flood_sim())
    rows.append(_stream_ab_row())
    try:
        from ray_tpu.devtools import res_debug

        rows.append({
            "metric": "serve_scale_res",
            "leaked_resources": sum(res_debug.outstanding().values()),
            "res_violations": len(res_debug.violations()),
        })
    except Exception as e:  # noqa: BLE001 — census never blocks rows
        rows.append({"metric": "serve_scale_res", "error": repr(e)[:200]})
    for r in rows:
        print(json.dumps(r), flush=True)
    return 0 if all("error" not in r for r in rows) else 1


def _stream_ab_row() -> dict:
    """Same-window streaming-vs-probe A/B on a real disagg deployment
    (1 prefill + 1 decode) plus a colocated streaming reference: the
    stream's first token leaves at prefill time over the reverse
    channel, so its p50 TTFT must hold the non-streaming probe's line
    — streaming is free, not a second hop."""
    try:
        import ray_tpu
        import ray_tpu.serve as serve
        from ray_tpu.serve.llm import build_llm_deployment
    except Exception as e:  # noqa: BLE001 — import gap -> error row
        return {"metric": "serve_scale_stream", "error": repr(e)[:200]}

    ek = dict(max_batch=4, max_len=288,
              prompt_buckets=[16, 32, 64, 128, 256], decode_chunk=4,
              prefill_chunk=32, seed=0)
    measure_s = 10.0
    row = {"metric": "serve_scale_stream"}
    try:
        ray_tpu.init(num_cpus=24)
        try:
            colo = serve.run(build_llm_deployment(
                name="scstcolo", engine_kwargs=ek))
            dis = serve.run(build_llm_deployment(
                name="scstdis", disaggregated=True,
                num_prefill_replicas=1, num_decode_replicas=1,
                engine_kwargs=ek))
            warm = {"prompt_ids": [7] * 16, "max_new_tokens": 4}
            colo.remote(dict(warm)).result(timeout=600)
            dis.remote(dict(warm)).result(timeout=600)

            def _stream_once(h, i, new_tokens):
                req = {"prompt_ids": [(i * 11 + j) % 251 + 1
                                      for j in range(16)],
                       "max_new_tokens": new_tokens}
                t0 = time.perf_counter()
                first = last = None
                n = 0
                for _ in h.options("stream", stream=True).remote(req):
                    last = time.perf_counter()
                    if first is None:
                        first = last
                    n += 1
                ttft = (first - t0) * 1e3
                tpot = ((last - first) / max(1, n - 1)) * 1e3
                return ttft, tpot, n

            for name, h in (("colo", colo), ("disagg", dis)):
                ttfts, tpots, sprobes, probes = [], [], [], []
                t_end = time.monotonic() + measure_s
                i = 0
                while time.monotonic() < t_end:
                    # Interleave a full stream, a STREAMED probe and a
                    # non-streaming probe: the A/B shares the window and
                    # the replica state, and probe-vs-stream-probe is
                    # the same request shape (completes at token 1), so
                    # any gap is the streaming plumbing itself.
                    ttft, tpot, n = _stream_once(h, i, 24)
                    ttfts.append(ttft)
                    tpots.append(tpot)
                    sprobes.append(_stream_once(h, i, 1)[0])
                    t0 = time.perf_counter()
                    h.remote({"prompt_ids": [3] * 16,
                              "max_new_tokens": 1}).result(timeout=300)
                    probes.append((time.perf_counter() - t0) * 1e3)
                    i += 1
                for k, vals in (("stream_p50_ttft_ms", ttfts),
                                ("stream_p50_tpot_ms", tpots),
                                ("stream_probe_p50_ttft_ms", sprobes),
                                ("probe_p50_ttft_ms", probes)):
                    vals.sort()
                    row[f"{name}_{k}"] = round(vals[len(vals) // 2], 2)
                row[f"{name}_streams"] = len(ttfts)
        finally:
            try:
                serve.shutdown()
            finally:
                ray_tpu.shutdown()
    except Exception as e:  # noqa: BLE001 — cluster gap -> error row
        row["error"] = repr(e)[:200]
    return row


def _serve_scale_rows() -> list:
    try:
        proc = _run(["--serve-scale-child"], SERVE_SCALE_TIMEOUT_S,
                    env_extra={"JAX_PLATFORMS": "cpu",
                               "RTPU_DEBUG_RES": "1"})
    except subprocess.TimeoutExpired:
        return [{"metric": "serve_scale",
                 "error": f"timeout {SERVE_SCALE_TIMEOUT_S}s"}]
    lines = _json_lines(proc.stdout)
    if lines and proc.returncode == 0:
        return lines
    tail = (proc.stderr or proc.stdout).strip().splitlines()[-3:]
    out = lines or []
    out.append({"metric": "serve_scale",
                "error": "rc=%d: %s" % (proc.returncode,
                                        " | ".join(tail))})
    return out


def _merge_serve_scale_rows(rows: list) -> dict:
    by = {r.get("metric"): r for r in rows}
    merged: dict = {"metric": "serve_scale"}
    err = next((r["error"] for r in rows if "error" in r), None)
    if err:
        merged["error"] = err
    lo = by.get("serve_scale_router_100", {})
    hi = by.get("serve_scale_router_10000", {})
    if lo.get("decisions_per_s") and hi.get("decisions_per_s"):
        merged["router_decisions_per_s"] = hi["decisions_per_s"]
        merged["router_decisions_per_s_100"] = lo["decisions_per_s"]
        # ~1.0 == flat: choose() cost held while the snapshot set grew
        # 100x (the O(touched) acceptance bar is 0.8+).
        merged["router_scale_flatness"] = round(
            hi["decisions_per_s"] / lo["decisions_per_s"], 3)
        merged["router_avg_candidates_scored_10k"] = \
            hi.get("avg_candidates_scored")
    qos = by.get("serve_scale_qos", {})
    for src, dst in (("good_p99_ttft_ms", "serve_qos_good_p99_ttft_ms"),
                     ("flood_p99_ttft_ms",
                      "serve_qos_flood_p99_ttft_ms"),
                     ("flood_shed", "serve_qos_flood_shed")):
        if qos.get(src) is not None:
            merged[dst] = qos[src]
    st = by.get("serve_scale_stream", {})
    if "error" not in st:
        for src, dst in (
                ("disagg_stream_p50_ttft_ms",
                 "serve_stream_disagg_p50_ttft_ms"),
                ("disagg_stream_p50_tpot_ms",
                 "serve_stream_disagg_p50_tpot_ms"),
                ("disagg_stream_probe_p50_ttft_ms",
                 "serve_stream_disagg_probe_p50_ttft_ms"),
                ("disagg_probe_p50_ttft_ms",
                 "serve_disagg_probe_p50_ttft_ms"),
                ("colo_stream_p50_ttft_ms",
                 "serve_stream_colo_p50_ttft_ms")):
            if st.get(src) is not None:
                merged[dst] = st[src]
    res = by.get("serve_scale_res", {})
    if res.get("leaked_resources") is not None:
        merged["serve_scale_leaked_resources"] = res["leaked_resources"]
    return merged


def serve_scale_main() -> int:
    rows = _serve_scale_rows()
    for r in rows:
        print(json.dumps(r), flush=True)
    print(json.dumps(_merge_serve_scale_rows(rows)))
    return 0 if all("error" not in r for r in rows) else 1


# --------------------------------------------------------------------------
# parent supervisor
# --------------------------------------------------------------------------

def accel_holders() -> list:
    """Which processes hold TPU device files open (/dev/accel*, /dev/vfio*).
    A wedged holder from a previous run is the usual cause of
    'UNAVAILABLE: TPU backend setup/compile error'."""
    holders = []
    try:
        for pid in os.listdir("/proc"):
            if not pid.isdigit():
                continue
            fd_dir = f"/proc/{pid}/fd"
            try:
                for fd in os.listdir(fd_dir):
                    try:
                        tgt = os.readlink(os.path.join(fd_dir, fd))
                    except OSError:
                        continue
                    if "/dev/accel" in tgt or "/dev/vfio" in tgt:
                        try:
                            with open(f"/proc/{pid}/cmdline", "rb") as f:
                                cmd = f.read().replace(b"\0", b" ") \
                                    .decode(errors="replace").strip()[:200]
                        except OSError:
                            cmd = "?"
                        holders.append(
                            {"pid": int(pid), "device": tgt, "cmd": cmd})
                        break
            except OSError:
                continue
    except OSError:
        pass
    return holders


def _pin_platform() -> None:
    """The axon TPU plugin force-appends itself to jax_platforms at import
    time, overriding JAX_PLATFORMS=cpu — and a wedged tunnel then HANGS
    backend init. Honor an explicit cpu request."""
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")


def probe_main() -> None:
    """Cheap backend-liveness check: init + one tiny computation."""
    _pin_platform()
    import jax
    import jax.numpy as jnp

    d = jax.devices()
    x = float(jnp.ones(8).sum())
    assert x == 8.0
    print(f"probe-ok {d[0].platform} {d[0].device_kind}")


def _run(args: list, timeout_s: int, env_extra: dict = None):
    env = dict(os.environ)
    if env_extra:
        env.update(env_extra)
    return subprocess.run(
        [sys.executable, os.path.abspath(__file__)] + args,
        capture_output=True, text=True, timeout=timeout_s, env=env,
        cwd=os.path.dirname(os.path.abspath(__file__)))


def _json_lines(text: str) -> list:
    out = []
    for ln in text.splitlines():
        if ln.startswith("{"):
            try:
                out.append(json.loads(ln))
            except ValueError:
                pass
    return out


def main() -> int:
    errors = []
    rows = []
    for attempt in range(ATTEMPTS):
        # Phase 1: probe. A wedged axon tunnel HANGS in init (observed:
        # >20min asleep in nanosleep) rather than raising — without this,
        # each dead attempt burns the full measurement timeout.
        try:
            probe = _run(["--probe"], PROBE_TIMEOUT_S)
            if probe.returncode != 0:
                tail = (probe.stderr or probe.stdout).strip() \
                    .splitlines()[-4:]
                raise RuntimeError("probe rc=%d: %s"
                                   % (probe.returncode, " | ".join(tail)))
        except (subprocess.TimeoutExpired, RuntimeError) as e:
            msg = (f"attempt {attempt}: probe hang >{PROBE_TIMEOUT_S}s"
                   if isinstance(e, subprocess.TimeoutExpired) else
                   f"attempt {attempt}: {e}")
            errors.append(msg)
            print(msg + "; backing off", file=sys.stderr)
            if attempt < ATTEMPTS - 1:
                time.sleep(BACKOFFS_S[min(attempt, len(BACKOFFS_S) - 1)])
            continue
        # Phase 2: measurement.
        try:
            proc = _run(["--child"], CHILD_TIMEOUT_S)
        except subprocess.TimeoutExpired:
            errors.append(f"attempt {attempt}: timeout {CHILD_TIMEOUT_S}s")
            continue
        if proc.returncode == 0:
            rows = _json_lines(proc.stdout)
            break
        tail = (proc.stderr or proc.stdout).strip().splitlines()[-6:]
        errors.append(f"attempt {attempt} rc={proc.returncode}: "
                      + " | ".join(tail))
        print(f"bench attempt {attempt} failed (rc={proc.returncode}); "
              f"retrying", file=sys.stderr)
        if attempt < ATTEMPTS - 1:
            time.sleep(BACKOFFS_S[min(attempt, len(BACKOFFS_S) - 1)])

    if not rows:
        # Persistent failure: structured record, not a traceback. value 0.0
        # plus an explicit error field — never a silently-plausible number.
        print(json.dumps({
            "metric": "train_mfu_llama8b_proxy",
            "value": 0.0,
            "unit": "mfu",
            "vs_baseline": 0.0,
            "error": "TPU backend init failed after retries",
            "attempts": ATTEMPTS,
            "attempt_errors": errors[-2:],
            "accel_holders": accel_holders(),
        }))
        return 1

    for r in rows:  # echo the child's rows for human readers / logs
        print(json.dumps(r), flush=True)

    # Phase 3: serve stack bench on CPU (chip-independent; never blocks
    # the hardware rows).
    serve_row = None
    try:
        sproc = _run(["--serve-child"], SERVE_TIMEOUT_S,
                     env_extra={"JAX_PLATFORMS": "cpu"})
        if sproc.returncode == 0:
            lines = _json_lines(sproc.stdout)
            serve_row = lines[-1] if lines else None
        else:
            serve_row = {"metric": "serve_llm", "error": "rc=%d: %s" % (
                sproc.returncode,
                " | ".join((sproc.stderr or sproc.stdout)
                           .strip().splitlines()[-3:]))}
    except subprocess.TimeoutExpired:
        serve_row = {"metric": "serve_llm",
                     "error": f"timeout {SERVE_TIMEOUT_S}s"}
    if serve_row is not None:
        print(json.dumps(serve_row), flush=True)

    # Phase 3b: routed-serve sweep on CPU (multi-replica skewed-prefix
    # traffic, random vs pow-2 vs scored routing). Tracked from this PR.
    routed_rows: list = []
    try:
        routed_rows = _serve_routed_rows()
    except Exception as e:  # noqa: BLE001 — never blocks the bench
        routed_rows = [{"metric": "serve_routed", "error": repr(e)[:200]}]
    for r in routed_rows:
        print(json.dumps(r), flush=True)

    # Phase 4: locality-scheduling suite on CPU (multi-node in-process
    # cluster; chip-independent). Tracked round-over-round from this PR.
    loc_rows: list = []
    try:
        loc_rows = _locality_suite_rows()
    except Exception as e:  # noqa: BLE001 — never blocks the bench
        loc_rows = [{"metric": "locality_scheduling",
                     "error": repr(e)[:200]}]
    for r in loc_rows:
        print(json.dumps(r), flush=True)

    # Phase 5: dataplane suite on CPU (multi-writer store + pull + actor
    # args). Tracked round-over-round from this PR.
    dp_rows: list = []
    try:
        dp_rows = _dataplane_rows()
    except Exception as e:  # noqa: BLE001 — never blocks the bench
        dp_rows = [{"metric": "dataplane", "error": repr(e)[:200]}]
    for r in dp_rows:
        print(json.dumps(r), flush=True)

    # Phase 6: chaos-recovery suite on CPU (kill head / kill holder,
    # rolling upgrade, recovery times + lease-leak census). Tracked
    # from this PR.
    chaos_rows: list = []
    try:
        chaos_rows = _chaos_rows()
    except Exception as e:  # noqa: BLE001 — never blocks the bench
        chaos_rows = [{"metric": "chaos_recovery", "error": repr(e)[:200]}]
    for r in chaos_rows:
        print(json.dumps(r), flush=True)

    # Phase 7: head scale suite on CPU (100 simulated nodes, head
    # dispatch/directory/census hot paths). Tracked from this PR.
    scale_rows: list = []
    try:
        scale_rows = _scale_rows()
    except Exception as e:  # noqa: BLE001 — never blocks the bench
        scale_rows = [{"metric": "head_scale", "error": repr(e)[:200]}]
    for r in scale_rows:
        print(json.dumps(r), flush=True)

    # Phase 8: compiled-DAG channel suite on CPU (per-hop ring latency
    # vs task-RPC round trip). Tracked from this PR.
    dag_rows: list = []
    try:
        dag_rows = _dag_rows()
    except Exception as e:  # noqa: BLE001 — never blocks the bench
        dag_rows = [{"metric": "dag_channel", "error": repr(e)[:200]}]
    for r in dag_rows:
        print(json.dumps(r), flush=True)

    # Phase 9: disaggregated-serving TTFT sweep on CPU (colocated vs
    # disagg p99 TTFT under mixed long-prompt + long-decode load).
    disagg_rows: list = []
    try:
        disagg_rows = _serve_disagg_rows()
    except Exception as e:  # noqa: BLE001 — never blocks the bench
        disagg_rows = [{"metric": "serve_disagg", "error": repr(e)[:200]}]
    for r in disagg_rows:
        print(json.dumps(r), flush=True)

    # Phase 10: streaming-data suite on CPU (channel-vs-task shuffle
    # GB/s + double-buffered ingest A/B). Tracked from this PR.
    data_rows: list = []
    try:
        data_rows = _data_rows()
    except Exception as e:  # noqa: BLE001 — never blocks the bench
        data_rows = [{"metric": "data_executor", "error": repr(e)[:200]}]
    for r in data_rows:
        print(json.dumps(r), flush=True)

    # Phase 11: fleet KV tier A/B on CPU (spill/pull vs recompute,
    # replica kill mid-sweep). Tracked from this PR.
    kvf_rows: list = []
    try:
        kvf_rows = _kv_fleet_rows()
    except Exception as e:  # noqa: BLE001 — never blocks the bench
        kvf_rows = [{"metric": "kv_fleet", "error": repr(e)[:200]}]
    for r in kvf_rows:
        print(json.dumps(r), flush=True)

    # Phase 12: serve-scale suite on CPU (1M-session router sim at
    # 100 -> 10k snapshots, WFQ flood isolation, streaming disagg
    # TTFT/TPOT A/B). Tracked from this PR.
    svs_rows: list = []
    try:
        svs_rows = _serve_scale_rows()
    except Exception as e:  # noqa: BLE001 — never blocks the bench
        svs_rows = [{"metric": "serve_scale", "error": repr(e)[:200]}]
    for r in svs_rows:
        print(json.dumps(r), flush=True)

    # Final merged line (the driver parses the tail line): headline is the
    # 8B north star when it measured, else the 1B row.
    by_metric = {r.get("metric"): r for r in rows}
    head = by_metric.get("train_mfu_llama8b_proxy")
    if not head or not head.get("value"):
        head = by_metric.get("train_mfu_llama1b", rows[-1])
    merged = dict(head)
    r1b = by_metric.get("train_mfu_llama1b", {})
    merged.setdefault("device", r1b.get("device"))
    merged.setdefault("n_chips", r1b.get("n_chips"))
    merged["train_mfu_llama1b"] = r1b.get("value")
    dec = by_metric.get("llm_decode_tokens_per_s", {})
    merged["llm_decode_tokens_per_s"] = dec.get("value")
    decq = by_metric.get("llm_decode_tokens_per_s_int8", {})
    if "error" not in decq and decq.get("value"):
        merged["llm_decode_tokens_per_s_int8"] = decq.get("value")
        merged["llm_decode_int8_speedup"] = decq.get("speedup_vs_f32")
    decp = by_metric.get("llm_decode_tokens_per_s_paged", {})
    if "error" not in decp and decp.get("value"):
        merged["llm_decode_tokens_per_s_paged"] = decp.get("value")
        merged["llm_decode_paged_speedup"] = \
            decp.get("speedup_vs_unpaged")
    ops_merged = _merge_ops_rows(
        [r for r in rows if r.get("metric") in ("ops_microbench",
                                                "decode_matmul_gbps")])
    for k, v in ops_merged.items():
        if k not in ("metric", "error") and v is not None:
            merged[k] = v
    eng = by_metric.get("llm_engine", {})
    if "error" not in eng:
        for k in ("ttft_ms", "prefix_hit_rate"):
            merged[k] = eng.get(k)
        if eng.get("compiled_programs"):
            # Total steady-state programs the witnessed engine built —
            # tracked round-over-round so compile creep is visible in
            # the BENCH_r* tail line.
            merged["llm_engine_programs"] = \
                sum(eng["compiled_programs"].values())
        # The engine suite's decode row supersedes the legacy row when
        # the legacy one errored out.
        if not merged.get("llm_decode_tokens_per_s"):
            merged["llm_decode_tokens_per_s"] = \
                eng.get("llm_decode_tokens_per_s")
    spec = by_metric.get("llm_engine_spec", {})
    if "error" not in spec:
        merged["llm_spec_accept_rate"] = spec.get("llm_spec_accept_rate")
        merged["llm_spec_speedup"] = spec.get("spec_speedup")
        merged["llm_decode_tokens_per_s_spec"] = \
            spec.get("llm_decode_tokens_per_s")
    elif spec:
        merged["spec_error"] = spec["error"]
    mx_on = by_metric.get("llm_engine_mixed_chunked", {})
    mx_off = by_metric.get("llm_engine_mixed_unchunked", {})
    if "error" not in mx_on and mx_on.get("p99_tpot_ms") is not None:
        merged["llm_mixed_p99_tpot_ms_chunked"] = mx_on["p99_tpot_ms"]
        if mx_off.get("p99_tpot_ms") is not None:
            merged["llm_mixed_p99_tpot_ms_unchunked"] = \
                mx_off["p99_tpot_ms"]
        if mx_on.get("p99_tpot_flatness_vs_unchunked") is not None:
            merged["llm_mixed_p99_tpot_flatness"] = \
                mx_on["p99_tpot_flatness_vs_unchunked"]
    elif mx_on:
        merged["mixed_error"] = mx_on["error"]
    if serve_row and "error" not in serve_row:
        for k in ("serve_llm_requests_per_s", "serve_llm_tokens_per_s",
                  "serve_llm_p50_ttft_ms", "serve_llm_p99_ttft_ms"):
            merged[k] = serve_row.get(k)
    elif serve_row:
        merged["serve_error"] = serve_row["error"]
    routed_merged = _merge_serve_routed_rows(routed_rows)
    if "error" not in routed_merged:
        for k in ("serve_routed_tokens_per_s", "serve_routed_p99_ttft_ms",
                  "serve_prefix_affinity_hit_rate",
                  "serve_routed_tokens_per_s_random",
                  "serve_routed_p99_ttft_ms_random",
                  "serve_routed_speedup_vs_random"):
            if routed_merged.get(k) is not None:
                merged[k] = routed_merged[k]
    else:
        merged["serve_routed_error"] = routed_merged["error"]
    loc_merged = _merge_locality_rows(loc_rows)
    if "error" not in loc_merged:
        for k in ("locality_hit_rate", "object_bytes_pulled_per_task",
                  "object_bytes_pulled_per_task_random"):
            if loc_merged.get(k) is not None:
                merged[k] = loc_merged[k]
    else:
        merged["locality_error"] = loc_merged["error"]
    dp_merged = _merge_dataplane_rows(dp_rows)
    for k in ("single_put_gbps", "multi_put_gbps", "put_scaling_ratio",
              "pull_gbps", "actor_args_nn_per_s"):
        if dp_merged.get(k) is not None:
            merged[k] = dp_merged[k]
    if "error" in dp_merged:
        merged["dataplane_error"] = dp_merged["error"]
    ch_merged = _merge_chaos_rows(chaos_rows)
    for k in ("head_recovery_s", "object_reconstruction_s",
              "head_upgrade_s", "leaked_leases", "leaked_resources"):
        if ch_merged.get(k) is not None:
            merged[k] = ch_merged[k]
    if "error" in ch_merged:
        merged["chaos_error"] = ch_merged["error"]
    sc = next((r for r in scale_rows if r.get("metric") == "head_scale"),
              {})
    if "error" not in sc and sc.get("head_dispatch_us_p99") is not None:
        suffix = f"{sc.get('nodes', 0)}node"
        merged[f"head_dispatch_us_p99_{suffix}"] = \
            sc["head_dispatch_us_p99"]
        merged[f"head_census_ms_{suffix}"] = sc.get("head_census_ms")
        for k in ("head_dispatch_bypass_rate", "storm_tasks_per_s",
                  "storm_tasks_per_s_headpath", "head_rpcs_per_task",
                  "head_rpcs_per_task_headpath"):
            if sc.get(k) is not None:
                merged[k] = sc[k]
    elif sc:
        merged["scale_error"] = sc["error"]
    dg = next((r for r in dag_rows if r.get("metric") == "dag_channel"),
              {})
    if "error" not in dg and dg.get("dag_hop_us_p50_4KB") is not None:
        for k in ("dag_hop_us_p50_4KB", "task_rpc_us_p50_4KB",
                  "dag_hop_speedup_vs_rpc_4KB",
                  "dag_exec_speedup_vs_rpc_4KB",
                  "dag_hop_speedup_vs_rpc_256KB"):
            if dg.get(k) is not None:
                merged[k] = dg[k]
    elif dg:
        merged["dag_error"] = dg["error"]
    dis_merged = _merge_serve_disagg_rows(disagg_rows)
    if "error" not in dis_merged:
        for k in ("serve_colo_p99_ttft_ms", "serve_disagg_p99_ttft_ms",
                  "serve_colo_p50_ttft_ms", "serve_disagg_p50_ttft_ms",
                  "serve_disagg_ttft_flatness"):
            if dis_merged.get(k) is not None:
                merged[k] = dis_merged[k]
    else:
        merged["serve_disagg_error"] = dis_merged["error"]
    da = next((r for r in data_rows
               if r.get("metric") == "data_executor"), {})
    if "error" not in da and da.get("data_shuffle_gbps_channel") is not None:
        for k in ("data_shuffle_gbps_channel", "data_shuffle_gbps_task",
                  "data_shuffle_channel_speedup",
                  "data_ingest_steps_per_s_buffered",
                  "data_ingest_steps_per_s_inline",
                  "data_ingest_steps_per_s_roofline",
                  "data_ingest_overlap_speedup",
                  "data_ingest_efficiency"):
            if da.get(k) is not None:
                merged[k] = da[k]
    elif da:
        merged["data_error"] = da["error"]
    kvf_merged = _merge_kv_fleet_rows(kvf_rows)
    if "error" not in kvf_merged:
        for k in ("kv_fleet_hit_rate", "kv_fleet_hit_rate_off",
                  "kv_fleet_p50_ttft_ms_churn",
                  "kv_fleet_p50_ttft_ms_churn_off",
                  "kv_pull_vs_recompute_crossover_blocks"):
            if kvf_merged.get(k) is not None:
                merged[k] = kvf_merged[k]
    else:
        merged["kv_fleet_error"] = kvf_merged["error"]
    svs_merged = _merge_serve_scale_rows(svs_rows)
    for k in ("router_decisions_per_s", "router_decisions_per_s_100",
              "router_scale_flatness",
              "router_avg_candidates_scored_10k",
              "serve_qos_good_p99_ttft_ms",
              "serve_qos_flood_p99_ttft_ms", "serve_qos_flood_shed",
              "serve_stream_disagg_p50_ttft_ms",
              "serve_stream_disagg_p50_tpot_ms",
              "serve_stream_disagg_probe_p50_ttft_ms",
              "serve_disagg_probe_p50_ttft_ms",
              "serve_stream_colo_p50_ttft_ms",
              "serve_scale_leaked_resources"):
        if svs_merged.get(k) is not None:
            merged[k] = svs_merged[k]
    if "error" in svs_merged:
        merged["serve_scale_error"] = svs_merged["error"]
    print(json.dumps(merged))
    return 0


if __name__ == "__main__":
    if "--child" in sys.argv:
        sys.exit(child_main())
    if "--serve-child" in sys.argv:
        sys.exit(serve_child_main())
    if "--serve-routed-child" in sys.argv:
        sys.exit(serve_routed_child_main())
    if "--serve" in sys.argv:
        sys.exit(serve_routed_main())
    if "--engine" in sys.argv:
        sys.exit(engine_child_main())
    if "--ops" in sys.argv:
        sys.exit(ops_main())
    if "--locality-child" in sys.argv:
        sys.exit(locality_child_main())
    if "--locality" in sys.argv:
        sys.exit(locality_main())
    if "--dataplane-child" in sys.argv:
        sys.exit(dataplane_child_main())
    if "--dataplane" in sys.argv:
        sys.exit(dataplane_main())
    if "--chaos-child" in sys.argv:
        sys.exit(chaos_child_main())
    if "--chaos" in sys.argv:
        sys.exit(chaos_main())
    if "--scale-child" in sys.argv:
        sys.exit(scale_child_main())
    if "--scale" in sys.argv:
        sys.exit(scale_main())
    if "--dag-child" in sys.argv:
        sys.exit(dag_child_main())
    if "--dag" in sys.argv:
        sys.exit(dag_bench_main())
    if "--data-child" in sys.argv:
        sys.exit(data_child_main())
    if "--data" in sys.argv:
        sys.exit(data_bench_main())
    if "--serve-disagg-child" in sys.argv:
        sys.exit(serve_disagg_child_main())
    if "--serve-disagg" in sys.argv:
        sys.exit(serve_disagg_main())
    if "--kv-fleet-child" in sys.argv:
        sys.exit(kv_fleet_child_main())
    if "--kv-fleet" in sys.argv:
        sys.exit(kv_fleet_bench_main())
    if "--serve-scale-child" in sys.argv:
        sys.exit(serve_scale_child_main())
    if "--serve-scale" in sys.argv:
        sys.exit(serve_scale_main())
    if "--probe" in sys.argv:
        sys.exit(probe_main())
    sys.exit(main())
